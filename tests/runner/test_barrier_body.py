"""SparkBarrierBackend task-body tests without pyspark (SURVEY.md §4:
distributed semantics tested locally; VERDICT round-1 missing #2/#3/#4).

A faked BarrierTaskContext (threading.Barrier-backed allGather) drives the
real :func:`run_barrier_task` body across N threads: rendezvous ordering,
hostname-sorted rank stability, one-task-per-host enforcement, stdout
forwarding through the driver-side log relay, and rank-0 result plumbing.
``distributed_init`` is injected so no real jax.distributed job forms.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time

import cloudpickle
import pytest

from sparkdl_tpu.runner.backends import (
    _LogRelay,
    _ShipOutput,
    resolve_ranks,
    run_barrier_task,
)


class FakeBarrierTaskContext:
    """allGather + partitionId, semantics-matched to pyspark's barrier ctx:
    every task must call allGather; messages come back in partition order."""

    def __init__(self, partition_id: int, shared: dict):
        self._pid = partition_id
        self._shared = shared

    def partitionId(self) -> int:
        return self._pid

    def allGather(self, message: str) -> list:
        self._shared["msgs"][self._pid] = message
        self._shared["barrier"].wait(timeout=30)
        return [self._shared["msgs"][i] for i in sorted(self._shared["msgs"])]


def _drive(nprocs, fn, kwargs=None, hostnames=None, log_addr=None,
           preflight_opts=None):
    """Run the real barrier task body on nprocs threads; return
    (results_by_partition, init_records)."""
    payload = cloudpickle.dumps({"fn": fn, "kwargs": kwargs or {}})
    shared = {"msgs": {}, "barrier": threading.Barrier(nprocs)}
    results: list = [None] * nprocs
    errors: list = [None] * nprocs
    records: list = [None] * nprocs

    def make_init(i):
        def init(coordinator, n, rank):
            records[i] = (coordinator, n, rank)
        return init

    def task(i):
        ctx = FakeBarrierTaskContext(i, shared)
        try:
            results[i] = run_barrier_task(
                ctx, payload, nprocs,
                preflight_opts if preflight_opts is not None
                else {"skip": True},
                log_addr=log_addr,
                hostname=(hostnames[i] if hostnames else f"fake-w-{i}"),
                distributed_init=make_init(i),
            )
        except BaseException as e:  # noqa: BLE001 - surfaced to the test
            errors[i] = e

    threads = [threading.Thread(target=task, args=(i,)) for i in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    return results, errors, records


def test_rank0_result_comes_back_and_others_empty():
    results, errors, records = _drive(3, lambda x: {"got": x}, {"x": 7})
    assert errors == [None, None, None]
    non_empty = [r for r in results if r]
    assert len(non_empty) == 1
    assert pickle.loads(non_empty[0]) == {"got": 7}
    # every rank initialized against the same coordinator with its own rank
    coords = {c for c, _, _ in records}
    assert len(coords) == 1
    assert sorted(r for _, _, r in records) == [0, 1, 2]


def test_ranks_follow_natural_hostname_order_not_partition_order():
    # partition 0 lands on worker 10, partition 1 on worker 2, partition 2
    # on worker 0: natural hostname sort puts w-0 < w-2 < w-10, so ranks
    # must be [2, 1, 0] by partition — and rank 0 (partition 2) returns.
    hostnames = ["t1v-x-w-10", "t1v-x-w-2", "t1v-x-w-0"]
    results, errors, records = _drive(
        3, lambda: "hi", hostnames=hostnames
    )
    assert errors == [None, None, None]
    assert [r for _, _, r in records] == [2, 1, 0]
    assert results[2] and not results[0] and not results[1]
    # coordinator is the first host in natural order (w-0 = partition 2)
    assert all(c.startswith("t1v-x-w-0:") for c, _, _ in records)


def test_rank_assignment_stable_across_retry_with_shuffled_partitions():
    hosts = ["h-3", "h-1", "h-2"]
    _, _, first = _drive(3, lambda: None, hostnames=hosts)
    # "stage retry": same hosts, different partition placement
    shuffled = ["h-2", "h-3", "h-1"]
    _, _, second = _drive(3, lambda: None, hostnames=shuffled)
    rank_by_host_1 = {h: r for h, (_, _, r) in zip(hosts, first)}
    rank_by_host_2 = {h: r for h, (_, _, r) in zip(shuffled, second)}
    assert rank_by_host_1 == rank_by_host_2


def test_duplicate_host_placement_rejected():
    results, errors, _ = _drive(
        2, lambda: None, hostnames=["same-host", "same-host"]
    )
    assert all(e is not None for e in errors)
    assert "one barrier task per TPU host" in str(errors[0])


def test_resolve_ranks_direct():
    ranks, coord = resolve_ranks(["b:1", "a:2", "c:3"])
    assert ranks == [1, 0, 2]
    assert coord == "a:2"


def test_stdout_forwarded_to_driver_relay():
    # fd-level redirection is process-global, so this drives ONE task (in
    # production each barrier task is its own executor python worker). The
    # worker writes straight to fd 1 — the level the tee operates at.
    captured: list[str] = []
    relay = _LogRelay(sink=captured.append)
    try:
        def chatty():
            import os as _os

            _os.write(1, b"hello from the worker\n")
            return 1

        results, errors, _ = _drive(1, chatty, log_addr=relay.address)
        assert errors == [None]
        deadline = time.time() + 5
        while not relay.lines and time.time() < deadline:
            time.sleep(0.05)
    finally:
        relay.close()
    tagged = [l for l in relay.lines if "hello from the worker" in l]
    assert tagged and tagged[0].startswith("[rank 0] ")
    assert captured == list(relay.lines)


def test_ship_output_tags_ranks_sequentially():
    captured: list[str] = []
    relay = _LogRelay(sink=captured.append)
    try:
        import os as _os

        for rank in (0, 1):
            with _ShipOutput(relay.address, rank):
                _os.write(1, f"line from {rank}\n".encode())
        deadline = time.time() + 5
        while len(relay.lines) < 2 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        relay.close()
    assert "[rank 0] line from 0" in relay.lines
    assert "[rank 1] line from 1" in relay.lines


def test_log_relay_reaps_pump_threads():
    """A long job's worth of short-lived connections must not accumulate
    one thread per connection (VERDICT r2 weak #7): pumps remove
    themselves on disconnect."""
    import socket

    captured: list[str] = []
    relay = _LogRelay(sink=captured.append)
    port = int(relay.address.rsplit(":", 1)[1])
    try:
        for i in range(300):
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(f"line {i}\n".encode())
        deadline = time.time() + 10
        while (relay.live_pumps > 0 or len(relay.lines) < 300) \
                and time.time() < deadline:
            time.sleep(0.05)
    finally:
        relay.close()
    assert relay.live_pumps == 0
    assert len(relay.lines) == 300
    # no dead Thread objects retained either (the actual leak shape)
    assert len(relay._pumps) == 0


def test_verbosity_none_means_no_relay_and_still_works():
    results, errors, _ = _drive(2, lambda: "quiet", log_addr=None)
    assert errors == [None, None]
    assert pickle.loads([r for r in results if r][0]) == "quiet"


class FakeSparkSession:
    """Just enough of SparkSession.sparkContext.parallelize(...).barrier()
    .mapPartitions(...).collect() to drive the REAL SparkBarrierBackend.run
    body: each partition's closure runs on its own thread with a
    FakeBarrierTaskContext patched in via ``_get_barrier_context``."""

    def __init__(self, monkeypatch):
        self._mp = monkeypatch
        self.sparkContext = self

    def parallelize(self, data, n):
        self._n = len(list(data))
        return self

    def barrier(self):
        return self

    def mapPartitions(self, f):
        self._f = f
        return self

    def collect(self):
        from sparkdl_tpu.runner import backends

        shared = {"msgs": {}, "barrier": threading.Barrier(self._n)}
        local = threading.local()
        self._mp.setattr(
            backends, "_get_barrier_context", lambda: local.ctx
        )
        out: list = [None] * self._n
        errs: list = [None] * self._n

        def part(i):
            local.ctx = FakeBarrierTaskContext(i, shared)
            try:
                out[i] = list(self._f(iter(())))
            except BaseException as e:  # noqa: BLE001
                errs[i] = e

        threads = [
            threading.Thread(target=part, args=(i,)) for i in range(self._n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if any(errs):
            raise next(e for e in errs if e)
        return [x for chunk in out for x in chunk]


def test_spark_backend_run_body_with_fake_session(monkeypatch):
    """Covers SparkBarrierBackend.run end-to-end minus pyspark itself:
    payload pickling, relay lifecycle, barrier fan-out, rank-0 result."""
    from sparkdl_tpu.runner import backends

    # the real body calls jax.distributed.initialize — stub the jax module
    # it imports lazily by pointing run_barrier_task's default init at a
    # recorder via monkeypatching the function's caller path
    inits: list = []
    real_run = backends.run_barrier_task

    def patched_run(ctx, payload, nprocs, opts, log_addr=None, **kw):
        return real_run(
            ctx, payload, nprocs, {"skip": True}, log_addr=log_addr,
            hostname=f"fake-host-{ctx.partitionId()}",
            distributed_init=lambda c, n, r: inits.append((c, n, r)),
        )

    monkeypatch.setattr(backends, "run_barrier_task", patched_run)
    backend = backends.SparkBarrierBackend(
        spark_session=FakeSparkSession(monkeypatch)
    )
    result = backend.run(3, lambda a, b: a + b, {"a": 2, "b": 40},
                         verbosity="none")
    assert result == 42
    assert sorted(r for _, _, r in inits) == [0, 1, 2]


def test_ship_output_unreachable_relay_is_harmless():
    # a port with no listener: the context manager must degrade to no-op
    with socket.socket() as s:
        s.bind(("", 0))
        dead = f"localhost:{s.getsockname()[1]}"
    with _ShipOutput(dead, 0):
        print("still fine")
