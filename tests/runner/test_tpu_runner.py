"""TPURunner local-mode tests (SURVEY.md §4: HorovodRunner's np<0 local mode
is the multi-node-without-a-cluster story; here it really launches processes
and initializes the global JAX runtime across them)."""

import numpy as np
import pytest

from sparkdl_tpu import HorovodRunner, TPURunner


def _train_fn(scale=1.0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    assert jax.process_count() == 2
    x = jnp.ones(3) * (jax.process_index() + 1) * scale
    gathered = multihost_utils.process_allgather(x)
    return {
        "rank": jax.process_index(),
        "nprocs": jax.process_count(),
        "global_devices": jax.device_count(),
        "sum": float(gathered.sum()),
    }


@pytest.mark.slow
def test_local_mode_two_processes():
    hr = TPURunner(np=-2, devices_per_process=2)
    out = hr.run(_train_fn, scale=2.0)
    assert out["rank"] == 0  # rank 0's result comes back
    assert out["nprocs"] == 2
    assert out["global_devices"] == 4  # 2 procs x 2 fake devices
    # allgather saw both ranks: (1+2) * 3 elements * scale 2
    assert out["sum"] == pytest.approx(18.0)


@pytest.mark.slow
def test_failure_aborts_job():
    def boom():
        import jax  # noqa: F401  (join the job before dying)

        raise RuntimeError("worker exploded")

    with pytest.raises(RuntimeError, match="rank"):
        TPURunner(np=-2, timeout_s=120).run(boom)


def test_horovod_runner_alias():
    assert HorovodRunner is TPURunner


def test_np_zero_rejected():
    with pytest.raises(ValueError):
        TPURunner(np=0)


def test_positive_np_without_cluster():
    with pytest.raises(RuntimeError, match="cluster"):
        TPURunner(np=4).run(lambda: None)


def test_bad_verbosity_rejected():
    with pytest.raises(ValueError):
        TPURunner(np=-1, driver_log_verbosity="loud")


class _InlineBackend:
    """Runs the (possibly wrapped) fn in-process — isolates the
    metrics_summary wrapper from real process launching."""

    def run(self, nprocs, fn, kwargs, verbosity="all"):
        return fn(**kwargs)


def test_metrics_summary_logs_cross_host_rollup(caplog):
    """metrics_summary=True: after main returns, every rank joins the
    aggregate_across_hosts rollup of the metrics registry and rank 0
    logs it (single-process here: mean == min == max == local value)."""
    import json
    import logging

    from sparkdl_tpu.observability.registry import registry

    registry().reset()

    def main(n):
        registry().counter("sparkdl_rollup_probe_total").inc(n)
        return n * 2

    runner = TPURunner(np=-1, backend=_InlineBackend(),
                       metrics_summary=True)
    with caplog.at_level(logging.INFO, logger="sparkdl_tpu.metrics"):
        assert runner.run(main, n=3) == 6
    recs = [r for r in caplog.records if "all-host metrics" in r.message]
    assert recs, caplog.records
    agg = json.loads(recs[0].message.split("all-host metrics ", 1)[1])
    assert agg["sparkdl_rollup_probe_total"] == {
        "mean": 3.0, "min": 3.0, "max": 3.0,
    }
    # default stays off: no wrapper, no rollup logline
    caplog.clear()
    registry().reset()
    with caplog.at_level(logging.INFO, logger="sparkdl_tpu.metrics"):
        TPURunner(np=-1, backend=_InlineBackend()).run(main, n=1)
    assert not [r for r in caplog.records if "all-host metrics" in r.message]
