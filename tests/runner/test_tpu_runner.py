"""TPURunner local-mode tests (SURVEY.md §4: HorovodRunner's np<0 local mode
is the multi-node-without-a-cluster story; here it really launches processes
and initializes the global JAX runtime across them)."""

import numpy as np
import pytest

from sparkdl_tpu import HorovodRunner, TPURunner


def _train_fn(scale=1.0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    assert jax.process_count() == 2
    x = jnp.ones(3) * (jax.process_index() + 1) * scale
    gathered = multihost_utils.process_allgather(x)
    return {
        "rank": jax.process_index(),
        "nprocs": jax.process_count(),
        "global_devices": jax.device_count(),
        "sum": float(gathered.sum()),
    }


@pytest.mark.slow
def test_local_mode_two_processes():
    hr = TPURunner(np=-2, devices_per_process=2)
    out = hr.run(_train_fn, scale=2.0)
    assert out["rank"] == 0  # rank 0's result comes back
    assert out["nprocs"] == 2
    assert out["global_devices"] == 4  # 2 procs x 2 fake devices
    # allgather saw both ranks: (1+2) * 3 elements * scale 2
    assert out["sum"] == pytest.approx(18.0)


@pytest.mark.slow
def test_failure_aborts_job():
    def boom():
        import jax  # noqa: F401  (join the job before dying)

        raise RuntimeError("worker exploded")

    with pytest.raises(RuntimeError, match="rank"):
        TPURunner(np=-2, timeout_s=120).run(boom)


def test_horovod_runner_alias():
    assert HorovodRunner is TPURunner


def test_np_zero_rejected():
    with pytest.raises(ValueError):
        TPURunner(np=0)


def test_positive_np_without_cluster():
    with pytest.raises(RuntimeError, match="cluster"):
        TPURunner(np=4).run(lambda: None)


def test_bad_verbosity_rejected():
    with pytest.raises(ValueError):
        TPURunner(np=-1, driver_log_verbosity="loud")
