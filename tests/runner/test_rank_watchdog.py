"""Rank watchdog (`LocalProcessBackend(straggler_grace_s=...)`): once
the first rank exits cleanly, survivors past the grace window are torn
down as hung instead of holding the job until the global ``timeout_s``.

Tested at the ``_wait_all`` layer with plain subprocesses — the watchdog
is pure process supervision, no JAX required."""

import subprocess
import sys
import time

from sparkdl_tpu.runner.backends import _wait_all


def _proc(code: str) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-c", code])


def test_hung_rank_torn_down_after_grace():
    procs = [
        _proc("pass"),                        # rank 0 exits immediately
        _proc("import time; time.sleep(60)"),  # rank 1 wedged
    ]
    t0 = time.monotonic()
    failed = _wait_all(procs, timeout_s=60.0, straggler_grace_s=0.3)
    elapsed = time.monotonic() - t0
    assert failed == [1]
    # the whole point: teardown on the grace window, not timeout_s
    assert elapsed < 10.0, elapsed
    procs[1].wait(timeout=5)  # actually killed, not left running


def test_disabled_watchdog_waits_for_stragglers():
    procs = [
        _proc("pass"),
        _proc("import time; time.sleep(0.8)"),  # slow but legit
    ]
    failed = _wait_all(procs, timeout_s=30.0, straggler_grace_s=None)
    assert failed == []  # default behavior unchanged: skew tolerated


def test_skew_within_grace_is_not_killed():
    procs = [
        _proc("pass"),
        _proc("import time; time.sleep(0.4)"),
    ]
    failed = _wait_all(procs, timeout_s=30.0, straggler_grace_s=5.0)
    assert failed == []


def test_failed_rank_still_aborts_job():
    # the watchdog must not mask the existing first-failure abort
    procs = [
        _proc("raise SystemExit(3)"),
        _proc("import time; time.sleep(60)"),
    ]
    failed = _wait_all(procs, timeout_s=60.0, straggler_grace_s=30.0)
    assert 0 in failed
    procs[1].wait(timeout=5)  # peers killed on first failure
