"""HPO trials routed through TPURunner (VERDICT round-1 weak #9: the
reference's Hyperopt+HorovodRunner nesting — SURVEY.md 2.13, BASELINE.md
configs[5] — must be exercised, not just documented)."""

from __future__ import annotations

import pytest

from sparkdl_tpu.hpo import Trials, fmin, hp
from sparkdl_tpu.runner import TPURunner


def _distributed_objective(lr):
    """One HPO trial = one 2-process TPURunner job: each rank fits a tiny
    quadratic with the trial's lr, grads psum'd across ranks; rank 0
    returns the final loss the sweep minimises."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    assert jax.process_count() == 2

    w = jnp.asarray(5.0)
    for _ in range(20):
        g = 2 * w  # d/dw of w^2
        g = multihost_utils.process_allgather(g[None]).mean()
        w = w - lr * g
    return {"loss": float(w ** 2), "nprocs": jax.process_count()}


@pytest.mark.slow
def test_fmin_with_tpurunner_trials():
    runner = TPURunner(np=-2, timeout_s=300)
    trials = Trials()

    def objective(params):
        out = runner.run(_distributed_objective, lr=params["lr"])
        assert out["nprocs"] == 2  # the trial really ran distributed
        return out

    # seed=1 draws choice indices [0, 1]: both lr values really run (a
    # seed whose draws collide would make the best-pick assertion vacuous)
    best = fmin(
        objective,
        {"lr": hp.choice("lr", [0.4, 0.05])},
        max_evals=2,
        seed=1,
        use_hyperopt=False,
        trials=trials,
    )
    assert len(trials.trials) == 2
    assert all(t["status"] == "ok" for t in trials.trials)
    losses = {t["params"]["lr"]: t["loss"] for t in trials.trials}
    assert set(losses) == {0.4, 0.05}  # both candidates actually ran
    # w shrinks by (1-2*lr) per step: lr=0.4 -> 0.2x/step beats 0.05 ->
    # 0.9x/step; the sweep must pick the empirically-lower loss.
    assert best["lr"] == min(losses, key=losses.get) == 0.4
