"""registerKerasImageUDF tests (SURVEY.md §4, [U: python/tests/udf/
keras_image_model_test.py]): registry round-trip, oracle vs direct predict,
preprocessor composition."""

import numpy as np
import pytest

from sparkdl_tpu import registerKerasImageUDF
from sparkdl_tpu.dataframe.local import LocalDataFrame
from sparkdl_tpu.image.imageIO import imageArrayToStructBGR
from sparkdl_tpu.udf.registry import applyUDF, getUDF, listUDFs

SIZE = 8


@pytest.fixture(scope="module")
def model():
    import keras

    return keras.Sequential(
        [
            keras.layers.Input((SIZE, SIZE, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(4, activation="softmax"),
        ]
    )


@pytest.fixture(scope="module")
def image_rows():
    rng = np.random.default_rng(2)
    return [
        {"image": imageArrayToStructBGR(
            rng.integers(0, 256, (SIZE, SIZE, 3), dtype=np.uint8)
        )}
        for _ in range(4)
    ]


def test_register_and_apply(model, image_rows):
    registerKerasImageUDF("score_img", model)
    assert "score_img" in listUDFs()
    df = LocalDataFrame.from_rows(image_rows, num_partitions=2)
    out = applyUDF("score_img", df, "image", "probs").collect()

    from sparkdl_tpu.image.imageIO import imageStructToArray

    batch = np.stack(
        [imageStructToArray(r["image"])[..., ::-1] for r in image_rows]
    ).astype(np.float32)
    oracle = np.asarray(model.predict(batch, verbose=0))
    got = np.stack([r["probs"] for r in out])
    np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-5)


def test_preprocessor_composes(model, image_rows):
    registerKerasImageUDF("score_scaled", model, preprocessor=lambda x: x / 255.0)
    udf = getUDF("score_scaled")
    got = udf(image_rows[0]["image"])

    from sparkdl_tpu.image.imageIO import imageStructToArray

    arr = imageStructToArray(image_rows[0]["image"])[..., ::-1].astype(np.float32)
    oracle = model.predict((arr / 255.0)[None], verbose=0)[0]
    np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-5)


def test_model_from_file(model, tmp_path, image_rows):
    path = str(tmp_path / "m.keras")
    model.save(path)
    registerKerasImageUDF("score_from_file", path)
    got = getUDF("score_from_file")(image_rows[0]["image"])
    assert got.shape == (4,)


def test_unknown_udf_rejected():
    with pytest.raises(KeyError, match="no UDF named"):
        getUDF("definitely_not_registered")
