"""TF-ingestion hardware smoke (SURVEY.md §7 hard part 1; VERDICT #8).

Builds a tiny MLP as a frozen TF-v1 GraphDef, ingests it through
``TFInputGraph``/``GraphFunction.to_jax`` (the jax2tf.call_tf lowering),
jits it on the default platform (the real TPU chip under the driver), and
asserts the device result matches the TF session oracle. Prints ONE JSON
line like bench.py.

This is the proof that the reference's "run an arbitrary frozen TF graph"
path executes ON TPU, not just in the CPU suite.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import os

    import jax

    # sitecustomize pre-selects the TPU platform; honor an explicit
    # JAX_PLATFORMS (same contract as bench.py) so CPU smokes stay on CPU.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import tensorflow as tf

    from sparkdl_tpu.graph.builder import IsolatedSession
    from sparkdl_tpu.graph.input import TFInputGraph

    rows = int(os.environ.get("BENCH_BATCH", 256))
    rng = np.random.default_rng(0)
    w1 = rng.standard_normal((16, 64)).astype(np.float32) * 0.3
    w2 = rng.standard_normal((64, 8)).astype(np.float32) * 0.3

    with IsolatedSession() as sess:
        x = tf.compat.v1.placeholder(tf.float32, [None, 16], name="x")
        h = tf.nn.relu(tf.matmul(x, tf.constant(w1)))
        y = tf.nn.softmax(tf.matmul(h, tf.constant(w2)), name="y")
        gfn = sess.asGraphFunction([x], [y])
        batch = rng.standard_normal((rows, 16)).astype(np.float32)
        oracle = sess.run(y, feed_dict={x: batch})

    tig = TFInputGraph.fromGraphDef(gfn.graph_def, ["x:0"], ["y:0"])
    to_jax = tig.to_jax()
    fn = jax.jit(lambda a: to_jax(a)[0])

    xb = jax.device_put(batch)
    out = np.asarray(fn(xb))
    ok = np.allclose(out, oracle, atol=1e-5)

    t0 = time.perf_counter()
    steps = 50
    last = None
    for _ in range(steps):
        last = fn(xb)
    float(last.sum())  # forced scalar read pins the chain
    dt = time.perf_counter() - t0
    device_resident_rps = batch.shape[0] * steps / dt

    # -- autotuned streaming ingest (ISSUE 8): the same ingested graph,
    # -- host-fed row by row through the sparkdl_tpu/ingest pipeline
    # -- (bucketing batch -> staging ring/prefetch -> fused dispatch)
    # -- with every unpinned knob under the tuner. The headline value is
    # -- THIS path — the zero-config throughput the autotuner delivers.
    from sparkdl_tpu import ingest
    from sparkdl_tpu.observability import registry
    from sparkdl_tpu.transformers._inference import BatchedRunner

    tuner = ingest.default_tuner()
    tuner.interval_s = float(os.environ.get("BENCH_AUTOTUNE_INTERVAL", 0.2))
    runner = BatchedRunner(
        lambda b: to_jax(b["x"])[0], batch_size=rows, autotune=True)
    n_stream = int(os.environ.get("BENCH_STREAM_ROWS", rows * 40))
    feats = rng.standard_normal((n_stream, 16)).astype(np.float32)

    # warmup: compile every bucket the stream will see
    list(runner.run(iter([{"x": feats[0]}] * rows)))
    t0 = time.perf_counter()
    n_out = sum(1 for _ in runner.run(
        {"x": feats[i]} for i in range(n_stream)))
    stream_dt = time.perf_counter() - t0
    assert n_out == n_stream, (n_out, n_stream)
    streamed_rps = n_stream / stream_dt

    platform = jax.default_backend()
    print(json.dumps({
        "metric": f"TFInputGraph.to_jax ingested-MLP autotuned streaming "
                  f"ingest ({platform})",
        "value": round(streamed_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": 1.0 if ok else 0.0,
        "allclose_vs_tf_session": bool(ok),
        "device_resident_rows_per_sec": round(device_resident_rps, 1),
        # ISSUE 8: decision count + steady-state knobs, registry-sourced
        "autotune": ingest.autotune_telemetry(),
        "observability": registry().snapshot(),
    }))
    tuner.stop()
    if not ok:
        raise SystemExit("ingested graph result diverged from TF oracle")


if __name__ == "__main__":
    main()
