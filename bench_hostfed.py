"""Host-fed featurization benchmark: decode -> pack -> stage -> device ->
features (VERDICT round-1 next-step #4 / weak #8).

Measures the FULL ingest path the native bridge exists for: JPEG bytes on
the host, native C++ threaded decode+resize, native pack into the staging
ring, double-buffered device transfer (DeviceFeeder via BatchedRunner),
jitted InceptionV3 features back to host. Reports img/s plus the ring
telemetry and infeed-starvation %, as ONE JSON line.

NOTE on this sandbox: the TPU sits behind a relay whose host->device path
is ~18 MB/s, so on-TPU host-fed numbers here measure the tunnel, not the
framework (a 128x299x299x3 uint8 batch is ~34 MB ≈ 2 s of wire time). The
honest use of this bench in-sandbox is JAX_PLATFORMS=cpu (exercises every
host-side stage + a real device_put); on a real TPU host it runs as-is.
"""

from __future__ import annotations

import io
import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from sparkdl_tpu.models.registry import build_flax_model, get_entry
    from sparkdl_tpu.native import bridge
    from sparkdl_tpu.native import decode as native_decode
    from sparkdl_tpu.observability.metrics import StepMeter, compiled_flops
    from sparkdl_tpu.ops.preprocess import PREPROCESSORS
    from sparkdl_tpu.transformers._inference import BatchedRunner

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    n_images = int(os.environ.get("BENCH_IMAGES", 2048 if on_accel else 256))
    batch = int(os.environ.get("BENCH_BATCH", 128 if on_accel else 32))
    size = 299 if on_accel else 128

    # -- synthesize a JPEG corpus (the host-side input of SURVEY.md 3.1) --
    from PIL import Image

    rng = np.random.default_rng(0)
    jpegs = []
    for i in range(64):
        arr = (rng.random((size + 21, size + 40, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=85)
        jpegs.append(buf.getvalue())

    entry = get_entry("InceptionV3")
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    module, variables = build_flax_model(
        "InceptionV3", weights=None, include_top=False, dtype=dtype
    )
    preprocess = PREPROCESSORS[entry.preprocess]

    def apply_fn(b):
        feats, _ = module.apply(
            variables, preprocess(b["image"].astype(dtype)), train=False
        )
        return feats.astype(jnp.float32)

    # Autotuned ingest (ISSUE 8): the bench runs the SAME pipeline a
    # zero-config user gets — decode parallelism, staging depth, chain K
    # and packer threads all start at their defaults and the tuner
    # resizes them from the measured starvation / producer-blocked
    # shares. Env pins (SPARKDL_TPU_PREFETCH, SPARKDL_TPU_CHAIN_K,
    # BENCH_DECODE_PAR) exclude a knob from tuning.
    from sparkdl_tpu import ingest

    tuner = ingest.default_tuner()
    tuner.interval_s = float(os.environ.get("BENCH_AUTOTUNE_INTERVAL", 0.2))
    runner = BatchedRunner(apply_fn, batch_size=batch, autotune=True)
    flops_per_img = compiled_flops(
        apply_fn,
        {"image": jax.ShapeDtypeStruct((1, size, size, 3), jnp.uint8)},
    )
    meter = StepMeter(
        flops_per_example=flops_per_img, n_chips=1, warmup_steps=0,
    )

    use_native_decode = native_decode.available()

    def decode_one(raw):
        if use_native_decode:
            arr = native_decode.decode_resize(raw, size, size)
        else:
            arr = np.asarray(
                Image.open(io.BytesIO(raw)).resize((size, size)))
        return {"image": arr}

    def rows():
        # decode rides an ingest map stage whose parallelism is a live
        # tuner knob: when the feed starves the device, more decode
        # threads spin up — the tf.data AUTOTUNE win on the real decode
        # hot path. BENCH_DECODE_PAR pins it.
        pipe = ingest.Pipeline(
            (jpegs[i % len(jpegs)] for i in range(n_images)),
            name="hostfed",
        ).map(decode_one, max_parallelism=4, env_var="BENCH_DECODE_PAR",
              name="decode")
        pipe.autotune(True)
        return iter(pipe)

    from sparkdl_tpu.observability import registry

    def _series(snap, name, field="value"):
        fam = snap.get(name) or {}
        vals = fam.get("values") or {}
        series = vals.get("") or {}
        if isinstance(series, dict):
            return float(series.get("sum") or 0.0)
        return float(series or 0.0)

    def ring_telemetry(snap):
        """Ring counters straight off the observability registry (ISSUE
        4 satellite): the SAME series `/metrics` exposes, not bench-local
        bookkeeping — slot waits (transfer/compute behind) and consumer
        waits (infeed starvation) next to batches/bytes."""
        return {
            "batches": _series(snap, "sparkdl_ring_batches_total"),
            "bytes": _series(snap, "sparkdl_ring_bytes_total"),
            "slot_wait_s": _series(
                snap, "sparkdl_ring_slot_wait_seconds_total"),
            "consumer_wait_s": _series(
                snap, "sparkdl_ring_consumer_wait_seconds"),
            "prefetch_consumer_wait_s": _series(
                snap, "sparkdl_prefetch_consumer_wait_seconds"),
        }

    # warmup (compile every bucket it will see)
    list(runner.run({"image": np.zeros((size, size, 3), np.uint8)}
                    for _ in range(batch)))
    ring0 = ring_telemetry(registry().snapshot())

    t0 = time.perf_counter()
    n_out = 0
    with meter.step(examples=n_images):
        for _ in runner.run(rows()):
            n_out += 1
    dt = time.perf_counter() - t0
    assert n_out == n_images

    ring1 = ring_telemetry(registry().snapshot())
    ring = {k: ring1[k] - ring0[k] for k in ring1}
    ring_batches = int(ring["batches"])
    ring_mb = ring["bytes"] / 2**20
    # starvation share of this run's wall: how long the consumer sat
    # waiting on the feed (ring or Python prefetch, whichever path ran)
    starve_s = ring["consumer_wait_s"] + ring["prefetch_consumer_wait_s"]
    summary = meter.summary()

    # -- text variant: BERT featurization through the struct-of-tensors
    # -- ring (input_ids + attention_mask share one slot; VERDICT r2 #4)
    from sparkdl_tpu.models.bert import BertConfig, BertModel

    tcfg = BertConfig.tiny(vocab_size=1024) if not on_accel else BertConfig(
        vocab_size=30522, hidden_size=256, num_hidden_layers=4,
        num_attention_heads=4, intermediate_size=1024,
        max_position_embeddings=128,
    )
    tmodel = BertModel(tcfg)
    max_len = 128 if on_accel else 16
    tvars = tmodel.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, max_len), jnp.int32), jnp.ones((1, max_len), jnp.int32),
    )

    def text_apply(b):
        seq, _ = tmodel.apply(tvars, b["input_ids"], b["attention_mask"])
        m = b["attention_mask"][:, :, None].astype(jnp.float32)
        return (seq.astype(jnp.float32) * m).sum(1) / jnp.maximum(
            m.sum(1), 1.0)

    n_texts = n_images
    trunner = BatchedRunner(text_apply, batch_size=batch)

    def text_rows():
        for i in range(n_texts):
            n = int(rng.integers(4, max_len))
            ids = np.zeros(max_len, np.int32)
            ids[:n] = rng.integers(1, tcfg.vocab_size, n)
            yield {"input_ids": ids,
                   "attention_mask": (np.arange(max_len) < n)
                   .astype(np.int32)}

    list(trunner.run(
        {"input_ids": np.zeros(max_len, np.int32),
         "attention_mask": np.ones(max_len, np.int32)}
        for _ in range(batch)))
    tstats0 = dict(bridge.FEED_STATS)
    t0 = time.perf_counter()
    t_out = sum(1 for _ in trunner.run(text_rows()))
    t_dt = time.perf_counter() - t0
    assert t_out == n_texts
    text_ring = bridge.FEED_STATS["ring_streams"] - tstats0["ring_streams"]

    print(json.dumps({
        "metric": f"host-fed InceptionV3 featurization "
                  f"(decode->pack->ring->device->features, {platform}, "
                  f"{size}px, batch {batch})",
        "value": round(n_images / dt, 1),
        "unit": "images/sec",
        "vs_baseline": round(n_images / dt / 10_000.0, 4),
        "native_decode": use_native_decode,
        "ring_batches": ring_batches,
        "ring_mb": round(ring_mb, 1),
        # registry-sourced (ISSUE 4): the same series /metrics scrapes
        "ring_slot_wait_s": round(ring["slot_wait_s"], 4),
        "ring_consumer_wait_s": round(ring["consumer_wait_s"], 4),
        "infeed_starvation_share": round(min(1.0, starve_s / dt), 4),
        "mfu": summary.get("mfu"),
        "infeed_starvation_pct": summary.get("infeed_starvation_pct"),
        "text_variant": {
            "texts_per_sec": round(n_texts / t_dt, 1),
            "rode_ring": bool(text_ring),
        },
        # ISSUE 8: every tuning decision visible, steady-state knobs
        # embedded (registry-sourced, like dispatch_gap_ms elsewhere)
        "autotune": ingest.autotune_telemetry(),
        "observability": registry().snapshot(),
    }))
    tuner.stop()


if __name__ == "__main__":
    main()
