"""Secondary benchmark: ResNet50 training MFU (BASELINE.md north-star 2).

Prints one JSON line like bench.py (the driver contract runs bench.py; this
script is the training-side evidence). Measures the steady-state jitted
train step — bf16 ResNet50, SGD+momentum, device-resident batch — and
reports MFU via the framework's own StepMeter/compiled_flops meters
(observability.metrics), against the >=50% target from BASELINE.md.
"""

import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.models.resnet import ResNet50
    from sparkdl_tpu.observability.metrics import StepMeter, compiled_flops
    from sparkdl_tpu.train.vision import (
        make_resnet50_fused_train_step,
        make_vision_train_step,
    )

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    batch = int(os.environ.get("BENCH_BATCH", 256 if on_accel else 8))
    steps = int(os.environ.get("BENCH_STEPS", 10 if on_accel else 2))
    repeats = int(os.environ.get("BENCH_REPEATS", 3 if on_accel else 1))
    # BENCH_FUSED=1 runs the Pallas BN-epilogue step; NOT the default —
    # measured round 3, kernel islands inside the XLA conv program pay a
    # layout-conversion tax that outweighs the fused passes (PERF.md
    # "Round 3"). Default = the XLA lowering, the faster program today.
    fused = os.environ.get("BENCH_FUSED", "0") == "1"
    size = 224 if on_accel else 32
    dtype = jnp.bfloat16 if on_accel else jnp.float32

    model = ResNet50(num_classes=1000, include_top=True, dtype=dtype)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    # Partitioner layer (ISSUE 6): the bench artifact carries the
    # partition geometry, the registry-sourced rule hit-counts, and the
    # measured per-chip optimizer-state bytes — so the ZeRO memory win
    # (BENCH_FSDP=N shards the momentum along fsdp) is a number in the
    # bench trajectory, not a claim.
    from sparkdl_tpu.partition import (
        DataParallelPartitioner,
        SingleDevicePartitioner,
        make_mesh,
        rule_hit_counts,
    )

    fsdp = int(os.environ.get("BENCH_FSDP", "1"))
    if fsdp > 1:
        # the benched loop runs the ZeRO layout for real: params
        # replicated, momentum sharded, update math sharded by XLA
        partitioner = DataParallelPartitioner(
            make_mesh(dp=-1, fsdp=fsdp, devices=jax.local_devices()),
            zero_axis="fsdp",
        )
        params = partitioner.shard_params(params)
        batch_stats = partitioner.shard_replicated(batch_stats)
        opt_state = partitioner.shard_opt_state(opt_state)
    else:
        # nothing committed: the bench stays the exact single-chip
        # program of the pre-partitioner trajectory, and the JSON line
        # honestly reports no partition axes
        partitioner = SingleDevicePartitioner()
    opt_state_bytes = partitioner.export_opt_state_bytes(opt_state)
    train_step = (
        make_resnet50_fused_train_step(
            tx, num_classes=1000, dtype=dtype, donate=False
        )
        if fused else make_vision_train_step(model, tx, donate=False)
    )
    # FLOPs are ALWAYS counted on the unfused (pure-XLA) step:
    # cost_analysis reports Pallas custom calls as 0 FLOPs, which would
    # silently understate the fused path's MFU — the same semantic
    # program must yield the same denominator either way.
    flops_step = make_vision_train_step(model, tx, donate=False)

    rng = np.random.default_rng(0)
    x = jax.device_put(rng.random((batch, size, size, 3), np.float32))
    y = jax.device_put(rng.integers(0, 1000, batch).astype(np.int32))

    flops_per_step = compiled_flops(
        flops_step, params, batch_stats, opt_state, x, y
    )
    meter = StepMeter(flops_per_step=flops_per_step, n_chips=1)

    # The benched unit chains `steps` train steps inside one jit via
    # lax.scan (state-carried, so iterations can't collapse): this chip's
    # ~2.4 ms per-dispatch overhead and ~70 ms trailing-read RTT would
    # otherwise understate MFU (PERF.md measurement discipline). State is
    # donated per dispatch — the steady-state production shape.
    from jax import lax

    def _step(carry, batch):
        p, bs, o = carry
        p, bs, o, loss = train_step(p, bs, o, *batch)  # inlines under jit
        return (p, bs, o), loss

    if fsdp > 1:
        # pin the carried state to its ZeRO layout from inside the trace
        # (partitioner.wrap_step): without the constraint XLA may pick a
        # replicated sharding for the scan carry, and the loop would not
        # run the sharded layout the JSON line reports
        carry_shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, (params, batch_stats, opt_state)
        )
        _step = partitioner.wrap_step(_step, carry_shardings)

    def scanned(params, batch_stats, opt_state, x, y):
        def body(carry, _):
            return _step(carry, (x, y))

        (params, batch_stats, opt_state), losses = lax.scan(
            body, (params, batch_stats, opt_state), None, length=steps
        )
        return params, batch_stats, opt_state, losses[-1]

    scanned = jax.jit(scanned, donate_argnums=(0, 1, 2))

    # warmup / compile; the forced scalar read (not block_until_ready, whose
    # readiness signal is unreliable for large output trees on relayed
    # backends) drains the queue before timing starts.
    params, batch_stats, opt_state, loss = scanned(
        params, batch_stats, opt_state, x, y
    )
    float(loss)

    t0 = time.perf_counter()
    for _ in range(repeats):
        params, batch_stats, opt_state, loss = scanned(
            params, batch_stats, opt_state, x, y
        )
    float(loss)  # forced read: the dependency chain pins all steps behind it
    step_time = (time.perf_counter() - t0) / (steps * repeats)
    for _ in range(steps * repeats):
        meter.record(step_time, examples=batch)

    s = meter.summary()
    mfu = s.get("mfu")
    target = 0.50
    # Dispatch spine (ISSUE 3): each timed repeat was ONE dispatch fusing
    # `steps` scan-chained train steps; report the amortization the JSON
    # trajectory would otherwise lose.
    from sparkdl_tpu.runtime.dispatch import (
        calibrate_dispatch_gap,
        dispatch_count,
        overhead_share,
        record_dispatch,
    )

    total_wall = step_time * steps * repeats
    for _ in range(repeats):
        record_dispatch("train_bench", steps, total_wall / repeats)
    gap = calibrate_dispatch_gap()
    n_dispatches = dispatch_count("train_bench")
    print(
        json.dumps(
            {
                "metric": f"ResNet50 train MFU ({platform}, {size}px, "
                          f"batch {batch})",
                "value": round(mfu, 4) if mfu is not None else None,
                "unit": "MFU",
                "vs_baseline": round(mfu / target, 4) if mfu else None,
                "examples_per_sec_per_chip": s.get("examples_per_sec_per_chip"),
                "dispatch_count": n_dispatches,
                "dispatch_gap_ms": round(gap * 1e3, 4),
                "overhead_share": round(
                    overhead_share(n_dispatches, total_wall, gap) or 0.0, 4
                ),
                "opt_state_bytes_per_chip": opt_state_bytes,
                "partition_axes": partitioner.describe()["axes"],
                "partition_rule_hits": rule_hit_counts(),
            }
        )
    )


if __name__ == "__main__":
    main()
