#!/usr/bin/env bash
# Test runner (reference parity: [U: python/run-tests.sh], SURVEY.md 2.22).
# Runs the suite on a virtual 8-device CPU mesh (conftest.py forces
# JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count=8) so every
# dp/tp/sp/ep/pp collective path executes without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")"

# Two lanes (VERDICT r4 #8): the default lane skips @pytest.mark.slow —
# the multi-process elastic/preemption jobs and full-size model oracles —
# and finishes under 10 minutes (355 tests in 9:42, idle host,
# 2026-07-31). `./run-tests.sh --full` runs everything (what CI and the
# driver's `pytest tests/` do).
if [[ "${1:-}" == "--full" ]]; then
  shift
  python -m pytest tests/ -q "$@"
else
  python -m pytest tests/ -q -m "not slow" "$@"
fi

# Driver-contract smoke: bench prints exactly one JSON line; graft hooks
# compile entry() and run the 6-regime multichip dryrun.
JAX_PLATFORMS=cpu BENCH_STEPS=2 BENCH_BATCH=4 python bench.py | tail -1 | python -c '
import json, sys
line = sys.stdin.readline()
rec = json.loads(line)
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
print("bench.py contract OK")
'
# Local multi-chip DP hook: same contract, batch sharded over 8 fake chips.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  BENCH_STEPS=2 BENCH_BATCH=8 BENCH_DP_DEVICES=8 python bench.py | tail -1 | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
assert "over 8 devices" in rec["metric"], rec
print("bench.py dp contract OK")
'
# Online serving bench: same one-JSON-line contract; vs_baseline is the
# micro-batch / batch-of-1 throughput ratio under open-loop Poisson load.
JAX_PLATFORMS=cpu BENCH_REQUESTS=64 python bench_serving.py | tail -1 | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
assert "micro-batch" in rec["metric"], rec
print("bench_serving contract OK")
'
# Secondary benches keep the same one-JSON-line contract (values are
# CPU-smoke only; the real numbers come from the chip — PERF.md).
for b in bench_tf_ingest.py bench_hostfed.py; do
  JAX_PLATFORMS=cpu BENCH_IMAGES=64 BENCH_BATCH=16 python "$b" | tail -1 | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
print("contract OK:", rec["metric"][:60])
'
done

# The driver's EXACT call form: import the module, call dryrun_multichip(8)
# with however many devices this host exposes (1 here — JAX_PLATFORMS=cpu
# without a forced device count), so the self-provisioning re-exec path is
# what gets tested, not an env-prepared shortcut.
JAX_PLATFORMS=cpu python -c 'import __graft_entry__ as g; g.dryrun_multichip(8)'
SDL_SKIP_DRYRUN=1 python __graft_entry__.py
