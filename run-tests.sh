#!/usr/bin/env bash
# Test runner (reference parity: [U: python/run-tests.sh], SURVEY.md 2.22).
# Runs the suite on a virtual 8-device CPU mesh (conftest.py forces
# JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count=8) so every
# dp/tp/sp/ep/pp collective path executes without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")"

# Tier-1 gate 0 (ISSUE 11): sparkdl-lint — AST invariant checks for
# concurrency (lock discipline), donated-buffer safety, hot-loop
# blocking, metric-family drift, fault-site coverage, and the env-pin
# contract. Fails the whole run on any finding; the JSON report (incl.
# every suppression + its justification) is printed for triage.
# `./run-tests.sh --lint-only` is the fast pre-commit path.
LINT_REPORT="${LINT_REPORT:-/tmp/sparkdl-lint.json}"
if JAX_PLATFORMS=cpu python -m sparkdl_tpu.lint sparkdl_tpu/ tests/ \
    --output "$LINT_REPORT"; then
  echo "sparkdl-lint OK (report: $LINT_REPORT)"
else
  echo "sparkdl-lint FAILED — full report: $LINT_REPORT" >&2
  exit 1
fi
if [[ "${1:-}" == "--lint-only" ]]; then
  exit 0
fi

# Two lanes (VERDICT r4 #8): the default lane skips @pytest.mark.slow —
# the multi-process elastic/preemption jobs and full-size model oracles —
# and finishes under 10 minutes (355 tests in 9:42, idle host,
# 2026-07-31). `./run-tests.sh --full` runs everything (what CI and the
# driver's `pytest tests/` do).
if [[ "${1:-}" == "--full" ]]; then
  shift
  python -m pytest tests/ -q "$@"
else
  python -m pytest tests/ -q -m "not slow" "$@"
fi

# Driver-contract smoke: bench prints exactly one JSON line; graft hooks
# compile entry() and run the 6-regime multichip dryrun.
JAX_PLATFORMS=cpu BENCH_STEPS=2 BENCH_BATCH=4 python bench.py | tail -1 | python -c '
import json, sys
line = sys.stdin.readline()
rec = json.loads(line)
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
# ISSUE 2: every bench artifact carries the metrics-registry snapshot
assert "sparkdl_bench_images_total" in rec["observability"], rec.keys()
# ISSUE 3: the artifact attributes dispatch amortization, not just img/s
assert rec["dispatch_count"] == 2, rec
assert 0 <= rec["overhead_share"] <= 1, rec
assert "sparkdl_dispatches_total" in rec["observability"], rec.keys()
# ISSUE 11: static-analysis drift rides the trajectory; HEAD lints clean
assert rec["lint_findings_total"] == 0, rec["lint_findings_total"]
print("bench.py contract OK")
'
# Fused-dispatch smoke (ISSUE 3): a chained BatchedRunner.run must issue
# ~K-fold fewer device dispatches than the unchained runner on the same
# stream, with bitwise-identical outputs.
JAX_PLATFORMS=cpu python -c '
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.runtime.dispatch import dispatch_count
from sparkdl_tpu.transformers._inference import BatchedRunner
w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)), jnp.float32)
rows = [{"x": np.random.default_rng(i).standard_normal(8).astype(np.float32)}
        for i in range(32)]
base = list(BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=4,
                          data_parallel=False, chain_k=1).run(iter(rows)))
d0 = dispatch_count("batch")
assert d0 == 8, d0
got = list(BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=4,
                         data_parallel=False, chain_k=8).run(iter(rows)))
d1 = dispatch_count("batch") - d0
assert d1 == 1, d1  # 8 batches, one fused dispatch
for g, b in zip(got, base):
    np.testing.assert_array_equal(g, b)
print("fused-dispatch smoke OK: 8 dispatches -> 1 at K=8, bitwise equal")
'
# Async-completion smoke (ISSUE 4): the pipelined readback must keep at
# most `window` results in flight and match the blocking readback
# bitwise on a chained runner.
JAX_PLATFORMS=cpu python -c '
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.runtime.completion import AsyncFetcher
from sparkdl_tpu.transformers._inference import BatchedRunner

# window bound: pulls may never run more than `window` ahead of yields
pulled = 0
def source():
    global pulled
    for i in range(24):
        pulled += 1
        yield np.full((2,), float(i))
yielded = 0
for out in AsyncFetcher(window=4, path="smoke").stream(source()):
    np.testing.assert_array_equal(out, np.full((2,), float(yielded)))
    yielded += 1
    assert pulled - yielded <= 4, (pulled, yielded)
assert yielded == 24

# bitwise parity: async (default) vs blocking readback, chained K=8
w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)), jnp.float32)
rows = [{"x": np.random.default_rng(i).standard_normal(8).astype(np.float32)}
        for i in range(32)]
base = list(BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=4,
                          data_parallel=False, chain_k=8,
                          async_fetch=False).run(iter(rows)))
got = list(BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=4,
                         data_parallel=False, chain_k=8).run(iter(rows)))
for g, b in zip(got, base):
    np.testing.assert_array_equal(g, b)
print("async-completion smoke OK: <=4 in flight, bitwise equal at K=8")
'
# Replica-pool smoke (ISSUE 4): a 2-replica CPU pool serves a burst with
# BOTH replicas receiving work, then drains to zero depth.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 python -c '
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.serving import ReplicaPool, ServingEngine
w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)), jnp.float32)
pool = ReplicaPool(lambda b: jnp.tanh(b["x"] @ w), batch_size=8)
assert len(pool.replicas) == 2, len(pool.replicas)
pool.warmup({"x": np.zeros((8, 8), np.float32)})
with ServingEngine(pool, max_wait_s=0.002) as eng:
    futs = [eng.submit({"x": np.full((8,), float(i), np.float32)})
            for i in range(64)]
    for i, f in enumerate(futs):
        np.testing.assert_allclose(
            f.result(timeout=60),
            np.tanh(np.full((8,), float(i), np.float32) @ np.asarray(w)),
            rtol=1e-5)
    snap = eng.snapshot()
pool.close()
assert snap["replica_count"] == 2, snap
served = [r["dispatched"] for r in snap["replicas"]]
assert all(d > 1 for d in served), served  # burst hit BOTH replicas
assert all(r["depth"] == 0 and r["in_flight"] == 0
           for r in snap["replicas"]), snap["replicas"]
print("replica-pool smoke OK: burst over 2 replicas", served,
      "drained to zero depth")
'
# Local multi-chip DP hook: same contract, batch sharded over 8 fake chips.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  BENCH_STEPS=2 BENCH_BATCH=8 BENCH_DP_DEVICES=8 python bench.py | tail -1 | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
assert "over 8 devices" in rec["metric"], rec
print("bench.py dp contract OK")
'
# Sequence-parallel smoke (ISSUE 13): 2 forced CPU devices, sp=2
# spatial prefill vs sp=1 — greedy tokens bitwise on a prompt spanning
# >= 3 chunks, the prefix-cache hit preserved across the sharded
# gather, and the sp.permute/sp.gather chaos contract: an injected
# collective fault mid-prefill re-queues the victim (typed flight
# event, zero lost admitted requests, still bitwise).
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
SPARKDL_TPU_FAULT_PLAN="sp.permute:OSError@2;sp.gather:OSError@2" \
python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.observability.flight import flight_recorder
from sparkdl_tpu.serving import ContinuousGPTEngine

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
rng = np.random.default_rng(9)
shared = rng.integers(1, cfg.vocab_size, 10).tolist()
cases = [
    (list(rng.integers(1, cfg.vocab_size, 19)), 5),  # >= 3 chunks at 8
    (shared + rng.integers(1, cfg.vocab_size, 3).tolist(), 5),
    (shared + rng.integers(1, cfg.vocab_size, 2).tolist(), 4),  # hit
]

def run(sp):
    eng = ContinuousGPTEngine(
        cfg, variables, n_slots=2, max_len=64, kv_block_size=4,
        prefill_chunk=8, sp=(None if sp < 2 else sp), auto_start=False)
    futs = [eng.submit(p, n) for p, n in cases]
    for _ in range(500):
        eng.tick()
        if all(f.done() for f in futs):
            break
    outs = [np.asarray(f.result(timeout=0)) for f in futs]
    snap = eng.snapshot()
    eng.close()
    return outs, snap

outs1, _ = run(1)            # fault plan hits 1: sp sites never fire
outs2, snap2 = run(2)        # hits 2: one permute + one gather injected
assert all(np.array_equal(a, b) for a, b in zip(outs1, outs2)), \
    "sp=2 diverged from sp=1"
kv = snap2["kv"]
assert kv["prefix_hits"] > 0, kv       # hit survived the sharded gather
assert kv["sp"]["axis"] == 2, kv
assert kv["sp"]["handoffs"] >= len(cases), kv
assert kv["sp"]["staging_blocks_used"] == 0, kv  # all staging released
evs = [e for e in flight_recorder().events()
       if e.get("kind") == "sp.collective_failed"]
sites = {e["site"] for e in evs}
assert {"sp.permute", "sp.gather"} <= sites, sites
assert all(e["error"] == "SpCollectiveError" for e in evs), evs
print(f"sp smoke OK: sp=2 bitwise vs sp=1 across {len(cases)} requests "
      f"(3-chunk prompt, prefix hit {kv['prefix_hits']} tokens), "
      f"injected {sorted(sites)} faults -> re-queued, zero lost")
EOF

# Multi-host fabric smoke (ISSUE 14): (a) a 2-host fleet under the
# cache-aware router must beat round-robin's prefix hit rate on the
# identical shared-prefix workload, with tokens oracle-exact under both
# policies; (b) host-kill drill — one host hard-killed under load with
# injected host.submit faults riding: ZERO lost accepted requests
# (every Future resolves with the right tokens via failover), the dead
# host quarantines, and the router's postmortem bundle carries the
# failover sequence.
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="seed=3;host.submit:OSError@5;host.drain:OSError@1" \
python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.fabric import InProcessHost, Router
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel, generate
from sparkdl_tpu.observability.flight import flight_recorder
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.serving import ContinuousGPTEngine

flight_recorder().configure(settle_s=0.05, min_interval_s=0.0)
cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
rng = np.random.default_rng(13)
groups = [rng.integers(1, cfg.vocab_size, 8).tolist() for _ in range(2)]
seeds = [g + [int(rng.integers(1, cfg.vocab_size))] for g in groups]
followers = [g + rng.integers(1, cfg.vocab_size, 2).tolist()
             for g in groups for _ in range(3)]

def make_engine(host_id):
    return ContinuousGPTEngine(
        cfg, variables, n_slots=2, max_len=32, kv_block_size=4,
        idle_wait_s=0.001, host_id=host_id)

def hit_rate(engines):
    h = m = 0
    for e in engines:
        kv = e.snapshot()["kv"]
        h, m = h + kv["prefix_hits"], m + kv["prefix_misses"]
    return h / max(1, h + m)

def run(policy):
    engines = [make_engine(f"{policy}-{i}") for i in range(2)]
    with Router([InProcessHost(e) for e in engines],
                policy=policy, auto_refresh=False) as router:
        for p in seeds:
            router.submit({"prompt": p, "max_new_tokens": 3}).result(60)
        router.refresh()
        futs = [router.submit({"prompt": p, "max_new_tokens": 3})
                for p in followers]
        outs = [np.asarray(f.result(60)) for f in futs]
    rate = hit_rate(engines)
    for e in engines:
        e.close()
    return rate, outs

# (a) affinity beats round-robin, both oracle-exact. The fault plan's
# 5th host.submit hit injects an OSError mid-run: the failover path
# must absorb it (zero lost) while the comparison stays valid.
rr_rate, rr_outs = run("round_robin")
af_rate, af_outs = run("affinity")
assert af_rate > rr_rate, (af_rate, rr_rate)
for p, a, b in zip(followers, af_outs, rr_outs):
    want = np.asarray(generate(
        model, variables, jnp.asarray([p], jnp.int32), 3)[0, len(p):])
    np.testing.assert_array_equal(a, want)
    np.testing.assert_array_equal(b, want)

# (b) host-kill drill on a fresh 2-host fleet, plus a graceful drain
# retry through the injected host.drain fault.
registry().reset()
engines = [make_engine(f"kill-{i}") for i in range(2)]
hosts = [InProcessHost(e) for e in engines]
with Router(hosts, max_failures=3, probation_s=0.5,
            auto_refresh=False) as router:
    futs = []
    for i in range(24):
        futs.append((i, router.submit(
            {"prompt": [1 + (i % 9), 2, 3], "max_new_tokens": 2})))
        if i == 10:
            engines[0].close(drain=False, timeout_s=5)  # host dies
    for i, f in futs:
        got = np.asarray(f.result(60))  # zero lost: all resolve
        p = [1 + (i % 9), 2, 3]
        want = np.asarray(generate(
            model, variables, jnp.asarray([p], jnp.int32), 2)[0, 3:])
        np.testing.assert_array_equal(got, want)
    assert router._hosts["kill-0"].quarantined
    moved = router.drain_host("kill-1")  # retries the injected fault
    assert moved == 0  # nothing queued: traffic already drained

def bundle_ok():
    b = flight_recorder().last_bundle
    if b is None:
        return False
    kinds = [e.get("kind") for e in b["events"]]
    return ("fabric.host_quarantined" in kinds
            and "fabric.failover" in kinds)

import time
deadline = time.monotonic() + 10.0
while not bundle_ok():
    assert time.monotonic() < deadline, "postmortem bundle never settled"
    time.sleep(0.02)
snap = registry().snapshot()
inj = snap["sparkdl_faults_injected_total"]["values"]
assert inj.get('site="host.drain"', 0) >= 1, inj
ret = snap["sparkdl_retries_total"]["values"]
assert ret.get('site="host.drain",outcome="recovered"', 0) >= 1, ret
for e in engines:
    e.close(drain=False)
print(f"fabric smoke OK: affinity hit-rate {af_rate:.2f} > "
      f"round-robin {rr_rate:.2f} (oracle-exact both), host-kill -> "
      "zero lost + quarantine + postmortem, drain fault recovered")
EOF

# Router-tier smoke (ISSUE 19): a RouterGroup of 2 routers over one
# 2-host fleet. An injected router.route fault tears one member's
# placement mid-stream AND one router is hard-killed under load:
# every accepted request still resolves oracle-exact (zero lost —
# the group walks to the surviving member), and the steady-state
# digest refreshes ride the DELTA wire, not wholesale.
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="seed=7;router.route:OSError@5" \
python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.fabric import InProcessHost, Router, RouterGroup
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel, generate
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.serving import ContinuousGPTEngine

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
engines = [ContinuousGPTEngine(
    cfg, variables, n_slots=2, max_len=32, kv_block_size=4,
    idle_wait_s=0.001, host_id=f"rt-{i}") for i in range(2)]
routers = [Router([InProcessHost(e) for e in engines],
                  auto_refresh=False) for _ in range(2)]
group = RouterGroup(routers)
# seed, then refresh twice: the second sync must ride the journal
group.submit({"prompt": [7, 3, 9, 1, 5], "max_new_tokens": 2}).result(60)
group.refresh()
group.refresh()
snap = registry().snapshot()
delta_bytes = snap["sparkdl_fabric_digest_delta_bytes_total"][
    "values"].get("", 0)
assert delta_bytes > 0, "steady-state refresh never used the delta wire"
# 24 requests; the fault plan tears placement #5, router 0 dies at #10
futs = []
for i in range(24):
    futs.append((i, group.submit(
        {"prompt": [1 + (i % 9), 2, 3], "max_new_tokens": 2},
        session=f"conv-{i % 6}")))
    if i == 10:
        routers[0].close()   # router killed holding accepted work
for i, f in futs:
    got = np.asarray(f.result(60))  # zero lost: every Future resolves
    p = [1 + (i % 9), 2, 3]
    want = np.asarray(generate(
        model, variables, jnp.asarray([p], jnp.int32), 2)[0, 3:])
    np.testing.assert_array_equal(got, want)
assert routers[0].closed and not routers[1].closed
snap = registry().snapshot()
inj = snap["sparkdl_faults_injected_total"]["values"]
assert inj.get('site="router.route"', 0) >= 1, inj
disp = snap["sparkdl_fabric_router_dispatch_total"]["values"]
assert sum(disp.values()) >= 25, disp
group.close(close_members=True)
for e in engines:
    e.close(drain=False)
print(f"router-tier smoke OK: 24/24 oracle-exact through a torn "
      f"placement + a router kill (dispatch {dict(disp)}), "
      f"{delta_bytes:.0f}B of digest sync on the delta wire")
EOF

# Migration smoke (ISSUE 19): drain a host holding parked sessions ->
# the sessions re-park on the survivor through the handoff wire codec,
# and every turn-2 resume there (a) matches a never-migrated engine
# bitwise and (b) beats re-prefilling the transcript cold (the
# pre-migration cost) on wall clock.
JAX_PLATFORMS=cpu python - <<'EOF'
import time
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.fabric import InProcessHost, Router
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.serving import ContinuousGPTEngine

cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=3,
                num_heads=4, intermediate_size=256, max_seq_len=1024)
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
kw = dict(n_slots=2, max_len=352, kv_block_size=32, kv_blocks=24,
          host_kv_blocks=512, disk_kv_blocks=16, idle_wait_s=0.0005)
PLEN, NEW = 320, 8
rng = np.random.default_rng(19)
prompts = [rng.integers(1, cfg.vocab_size, PLEN).tolist() for _ in range(3)]
a = ContinuousGPTEngine(cfg, variables, host_id="mig-a", **kw)
b = ContinuousGPTEngine(cfg, variables, host_id="mig-b", **kw)
cold = ContinuousGPTEngine(cfg, variables, host_id="mig-cold", **kw)

def warm_conv(eng, park):
    p = rng.integers(1, cfg.vocab_size, PLEN).tolist()
    r = eng.submit(p, NEW).result(timeout=300).tolist()
    if park is None:
        return
    if park:
        eng.park_cold()
    eng.submit(p + r + [5], NEW).result(timeout=300)

warm_conv(a, None)          # compile A's prefill bucket
warm_conv(b, True)          # compile B's resume path (install + tail)
warm_conv(cold, False)      # compile the cold arm's full re-prefill
replies = [a.submit(p, NEW).result(timeout=300).tolist() for p in prompts]
a.park_cold()
with Router([InProcessHost(a), InProcessHost(b)],
            auto_refresh=False) as router:
    router.drain_host("mig-a")   # exports A's parked fleet onto B
mig = registry().snapshot()["sparkdl_kv_migrations_total"]["values"]
assert mig.get('outcome="exported"', 0) >= 3, mig
assert mig.get('outcome="imported"', 0) >= 3, mig
assert b.capacity()["kv_parked_sessions"] >= 3, b.capacity()

def timed(eng):
    outs, lats = [], []
    for p, r in zip(prompts, replies):
        t0 = time.perf_counter()
        outs.append(eng.submit(p + r + [5], NEW)
                    .result(timeout=300).tolist())
        lats.append(time.perf_counter() - t0)
    return outs, 1e3 * float(np.median(lats))

out_b, resume_p50 = timed(b)       # migrated resume: unpark + tail
out_cold, reprefill_p50 = timed(cold)  # never saw the transcripts
assert out_b == out_cold, "migrated resume diverged from cold oracle"
assert b._kv_snapshot()["tiers"]["unparks"] > 0, "resume re-prefilled"
assert resume_p50 < reprefill_p50, (resume_p50, reprefill_p50)
for e in (a, b, cold):
    e.close(drain=False)
print(f"migration smoke OK: 3 parked sessions drained mig-a -> mig-b "
      f"over the wire codec; resume p50 {resume_p50:.1f}ms beats cold "
      f"re-prefill {reprefill_p50:.1f}ms, tokens bitwise")
EOF

# Elastic-autoscale smoke (ISSUE 15): a 1-replica pool + engine under
# manual controller ticks. (a) load step -> scale-up within a bounded
# tick count; (b) load drop -> drain-based scale-down with ZERO lost
# accepted requests (every Future resolves correctly); (c) an injected
# replica.scale_down fault defers the scale event (healthz degraded,
# nothing moves, nothing lost) and the retry lands clean; (d) every
# decision is visible in the flight ring and /healthz recovers to ok.
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="seed=7;autoscale.decide:RuntimeError@4;replica.scale_down:OSError@1;kv_pool.resize:OSError@9" \
python - <<'EOF'
import threading
import time
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.autoscale import AutoScaler, AutoscalePolicy
from sparkdl_tpu.observability.flight import flight_recorder, healthz_report
from sparkdl_tpu.serving import ReplicaPool, ServingEngine
from sparkdl_tpu.serving.kv_blocks import KVBlockPool

DIM = 8
W = jnp.asarray(np.random.default_rng(0).standard_normal((DIM, DIM)),
                jnp.float32) / DIM

def apply_fn(b):
    return jnp.tanh(b["x"] @ W)

pool = ReplicaPool(apply_fn, batch_size=8, n_replicas=1)
warm = {"x": np.zeros((8, DIM), np.float32)}
pool.warmup(warm)
engine = ServingEngine(pool, max_queue_depth=4096, max_wait_s=0.002)
kv = KVBlockPool(64, 4)
depth = [0.0]
scaler = AutoScaler(pool=pool, kv_pool=kv, kv_lock=threading.Lock(),
                    signals=lambda: (depth[0], 0.0),
                    policy=AutoscalePolicy(max_replicas=2, hysteresis=2,
                                           cooldown_ticks=1, tabu_ticks=2,
                                           kv_step_blocks=8),
                    warmup_arrays=warm)
futs = [engine.submit({"x": np.full((DIM,), float(i % 5), np.float32)})
        for i in range(64)]
# (a) load step: scale-up within N ticks (hysteresis 2 -> 2 ticks)
depth[0] = 40.0
ticks_to_scale = 0
for _ in range(6):
    scaler.tick(); ticks_to_scale += 1
    if len(pool.replicas) == 2:
        break
assert len(pool.replicas) == 2, "no scale-up under load step"
assert ticks_to_scale <= 3, f"scale-up took {ticks_to_scale} ticks"
# (b)+(c) load drop: the kv tier shrinks first (one step per cooldown
# window), then the FIRST replica scale-down attempt hits the injected
# replica.scale_down@1 fault -> the decision defers (nothing moves) and
# the retry lands clean; autoscale.decide@4 also defers one whole pass
# mid-sequence. Drive ticks until the pool is back to 1.
depth[0] = 0.0
saw_deferred = saw_degraded = False
deadline = time.monotonic() + 30.0
while len(pool.replicas) > 1 and time.monotonic() < deadline:
    scaler.tick()
    if scaler.state == "deferred":
        saw_deferred = True
        saw_degraded |= healthz_report()["status"] == "degraded"
    time.sleep(0.005)
assert len(pool.replicas) == 1, "no drain-based scale-down"
assert saw_deferred, "injected fault never deferred a scale decision"
assert saw_degraded, "deferred scale event did not degrade /healthz"
# ZERO lost: every accepted request resolves with the right answer
expect = {v: np.tanh(np.full((DIM,), float(v)) @ np.asarray(W))
          for v in range(5)}
for i, f in enumerate(futs):
    np.testing.assert_allclose(np.asarray(f.result(timeout=60)),
                               expect[i % 5], rtol=1e-5)
snap = engine.snapshot()
assert snap["completed"] == 64 and snap["failed"] == 0, snap
# (d) decisions visible; healthz recovered
kinds = [str(e.get("kind")) for e in flight_recorder().events()]
assert "autoscale.decision" in kinds
assert "autoscale.deferred" in kinds
assert "pool.scale_up" in kinds and "pool.scale_down" in kinds
for _ in range(4):
    scaler.tick()
assert scaler.state == "ok"
assert healthz_report()["status"] == "ok", healthz_report()
a = healthz_report()["autoscalers"]
assert a and a[0]["state"] == "ok", a
engine.close(); scaler.close(); pool.close()
print("autoscale smoke OK: step -> scale-up in "
      f"{ticks_to_scale} ticks, drop -> drain-based scale-down, "
      "injected scale_down/decide faults deferred (healthz degraded "
      "-> ok), 64/64 requests exact, decisions in flight ring")
EOF

# Disaggregated-serving smoke (ISSUE 16): (a) greedy tokens through a
# PrefillWorker -> int8 KVHandoff -> DecodeWorker chain are BITWISE
# identical to the colocated engine (prefix hits included, on both
# sides of the tier boundary); (b) a PhaseRouter stream under injected
# handoff.export AND handoff.install faults loses ZERO accepted
# requests — victims re-queue at the prefill tier's head and the
# counters reconcile exactly.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.disagg import DecodeWorker, PhaseRouter, PrefillWorker
from sparkdl_tpu.fabric.host import InProcessHost
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.reliability.faults import inject
from sparkdl_tpu.serving import ContinuousGPTEngine

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
KW = dict(n_slots=2, max_len=48, kv_block_size=4, prefill_chunk=8,
          kv_dtype="int8", kv_layout="paged")
rng = np.random.RandomState(3)
base = rng.randint(1, 50, size=12).tolist()
cases = [(base + rng.randint(1, 50, size=rng.randint(2, 6)).tolist(),
          int(rng.randint(2, 8))) for _ in range(8)]

# (a) bitwise across the split, int8 wire, shared-prefix workload
col = ContinuousGPTEngine(cfg, variables, **KW)
want = [np.asarray(col.submit(p, m).result(timeout=120))
        for p, m in cases]
col.close()
pre = PrefillWorker(cfg, variables, **KW)
dec = DecodeWorker(cfg, variables, **KW)
got, wire_bytes = [], 0
for p, m in cases:
    h = pre.submit(p, m).result(timeout=120)
    wire_bytes += h.wire_bytes
    got.append(np.asarray(dec.submit_handoff(h).result(timeout=120)))
assert all(np.array_equal(w, g) for w, g in zip(want, got)), \
    "tier split changed greedy tokens"
assert pre._prefix.hit_tokens > 0 and dec._prefix.hit_tokens > 0, \
    "prefix cache never hit across the boundary"
pre.close(); dec.close()

# (b) zero loss under both handoff fault sites + counters reconcile
pres = [PrefillWorker(cfg, variables, host_id=f"p{i}", **KW)
        for i in range(2)]
decs = [DecodeWorker(cfg, variables, host_id=f"d{i}", **KW)
        for i in range(2)]
pr = PhaseRouter([InProcessHost(e, host_id=e.host_id) for e in pres],
                 [InProcessHost(e, host_id=e.host_id) for e in decs],
                 auto_refresh=False, max_handoff_retries=4)
with inject("handoff.install%0.25;handoff.export@3;seed=11"):
    futs = [(pr.submit(p, m), m) for p, m in cases * 3]
    outs = [np.asarray(f.result(timeout=120)) for f, _ in futs]
for (f, m), out in zip(futs, outs):
    assert len(out) == m, (len(out), m)
snap = pr.snapshot()["disagg"]
assert snap["submitted"] == len(futs), snap
assert snap["completed"] == len(futs) and snap["failed"] == 0, snap
assert snap["requeues"] >= 1, "install faults never exercised requeue"
aborts = sum(e._export_aborts for e in pres)
pr.close()
for e in pres + decs:
    e.close()
print(f"disagg smoke OK: {len(cases)}/8 bitwise across the int8 split "
      f"({wire_bytes} wire bytes, prefix hits both tiers), "
      f"{len(futs)}/{len(futs)} under chaos (requeues={snap['requeues']}, "
      f"export aborts={aborts}, zero lost, counters reconcile)")
EOF

# Cross-host trace-stitching smoke (ISSUE 17): the split request over
# the REAL HTTP transport — a prefill-tier HostServer and a decode-tier
# HostServer on separate ports behind a PhaseRouter of HttpHostHandles.
# The serialized SpanContext rides the submit body and the KVHandoff
# wire dict, so fleet_trace(request_id) resolves to ONE stitched trace:
# BOTH tiers' spans, exactly one handoff.wire crossing, clock offsets
# estimated for both hosts, and a five-phase breakdown telescoping to
# the measured end-to-end latency.
JAX_PLATFORMS=cpu python - <<'EOF'
import time
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.disagg import DecodeWorker, PhaseRouter, PrefillWorker
from sparkdl_tpu.fabric.http import HostServer, HttpHostHandle
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.fleet import PHASES, FleetScraper

tracing.clear_trace()
tracing.enable_tracing()
cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
KW = dict(n_slots=2, max_len=48, kv_block_size=4, prefill_chunk=8,
          kv_dtype="int8", kv_layout="paged")
pre = PrefillWorker(cfg, variables, host_id="pre-0", **KW)
dec = DecodeWorker(cfg, variables, host_id="dec-0", **KW)
srv_p = HostServer(pre)
srv_d = HostServer(dec)
pr = PhaseRouter(
    [HttpHostHandle(f"http://127.0.0.1:{srv_p.port}", host_id="pre-0")],
    [HttpHostHandle(f"http://127.0.0.1:{srv_d.port}", host_id="dec-0")],
    auto_refresh=False)
try:
    t0 = time.monotonic()
    out = np.asarray(pr.submit(list(range(1, 11)), 4).result(timeout=120))
    e2e = time.monotonic() - t0
    assert len(out) == 4, out

    wire = [e for e in tracing.trace_events()
            if e["name"] == "handoff.wire"]
    assert len(wire) == 1, sorted(
        {e["name"] for e in tracing.trace_events()})
    rid = wire[0]["args"]["request_id"]

    scraper = FleetScraper.from_phase_router(pr)
    assert scraper.tier_of("pre-0") == "prefill"
    assert scraper.tier_of("dec-0") == "decode"
    stitched = scraper.fleet_trace(rid)
    names = [e["name"] for e in stitched["spans"]]
    assert names.count("handoff.wire") == 1, names
    assert "disagg.handoff_export" in names, names   # prefill tier ran
    assert "disagg.handoff_install" in names, names  # decode tier ran
    assert names.index("disagg.handoff_export") \
        < names.index("handoff.wire"), names
    # both hosts answered the offset probes; one process, so ~zero skew
    offs = scraper.clock_offsets()
    assert set(offs) == {"pre-0", "dec-0"}, offs
    assert all(abs(o) < 1e6 for o in offs.values()), offs
    phases = stitched["phases"]
    assert [(p["phase"], p["tier"]) for p in phases] == list(PHASES), \
        phases
    total = sum(p["seconds"] for p in phases)
    assert total > 0, phases
    assert abs(total - e2e) < 0.25 * e2e + 0.1, (total, e2e)
finally:
    pr.close()
    srv_p.close(); srv_d.close()
    pre.close(); dec.close()
    tracing.disable_tracing(); tracing.clear_trace()
print(f"disagg-trace smoke OK: split request over HTTP stitched to ONE "
      f"trace ({len(names)} spans, 1 handoff.wire crossing), phases "
      f"{total:.3f}s vs e2e {e2e:.3f}s")
EOF

# Online serving bench: same one-JSON-line contract; vs_baseline is the
# micro-batch / batch-of-1 throughput ratio under open-loop Poisson load.
# BENCH_SPEC_K/BENCH_KV_DTYPE are pinned: the contract below asserts the
# spec/quant sections, so the ambient environment must not disable them.
# BENCH_AUTOSCALE=1: the elastic-autoscaling section must emit scale
# events and the replica trajectory for the contract below.
# BENCH_DISAGG=1: the disaggregated-serving section must show the
# 3072-token prompt stream NOT moving interactive p95 past the
# colocated stall, and the int8 handoff moving >=3.5x fewer bytes.
# BENCH_PARK_DEPTH: the tiered-KV section must show turn-2 resume
# beating re-prefill at both depths with >=4x device-only sessions
# parked per chip.
# BENCH_ROUTERS=2: the scaled-router-tier section must show N=2
# placement agreement ~1, digest deltas >=10x smaller than wholesale
# per refresh, and the N=2 hit rate within 10% of single-router.
# BENCH_TENANTS=3: the multi-tenant QoS section must show the worst
# victim p95 within 10% of its flooder-free baseline while the
# flooder's ~10x overage sheds typed, and a driven brownout episode
# walking the ladder up and back to 0.
JAX_PLATFORMS=cpu BENCH_REQUESTS=64 BENCH_SPEC_K=4 BENCH_KV_DTYPE=int8 \
  BENCH_AUTOSCALE=1 BENCH_DISAGG=1 BENCH_PARK_DEPTH=8,16 \
  BENCH_ROUTERS=2 BENCH_TENANTS=3 \
  python bench_serving.py | tail -1 | python -c '
import json, os, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
assert "micro-batch" in rec["metric"], rec
# the serving spine must attribute the run: admission, latency, occupancy
obs = rec["observability"]
for key in ("sparkdl_queue_submitted_total", "sparkdl_serving_requests_total",
            "sparkdl_serving_latency_seconds",
            "sparkdl_serving_batch_occupancy_pct"):
    assert key in obs, (key, sorted(obs))
# ISSUE 3: serving dispatches counted + overhead share attributed
assert rec["dispatch_count"] > 0, rec
assert "sparkdl_dispatch_seconds" in obs, sorted(obs)
# ISSUE 4: async-completion + replica fields ride the artifact
assert 0 <= rec["fetch_wait_share"] <= 1, rec["fetch_wait_share"]
assert rec["replica_count"] == 1, rec["replica_count"]
assert "sparkdl_fetch_wait_seconds" in obs, sorted(obs)
# ISSUE 9: declared SLO (objective + rolling burn) and flight-ring volume
slo = rec["slo"]
assert slo["latency"]["threshold_s"] > 0, slo
assert 0 < slo["latency"]["target"] < 1, slo
assert slo["latency"]["burn_rate"] is not None, slo
assert slo["availability"]["burn_rate"] is not None, slo
assert isinstance(rec["flight_events_total"], int), rec["flight_events_total"]
assert rec["flight_events_total"] > 0, "flight ring saw no events"
# ISSUE 10: paged-KV section — shared-prefix hit rate, block accounting,
# chunked prefill, and the dense-vs-paged bitwise verdict
kp = rec["kv_paged"]
assert kp["paged_bitwise_vs_dense"] is True, kp
assert rec["prefix_hit_rate"] > 0.5, rec["prefix_hit_rate"]
assert rec["kv_blocks_used"] > 0, rec["kv_blocks_used"]
assert rec["prefill_chunks"] > 0, rec["prefill_chunks"]
assert "sparkdl_kv_blocks_used" in obs, sorted(obs)
assert "sparkdl_prefix_hits_total" in obs, sorted(obs)
# ISSUE 12: speculative decode + quantized KV — acceptance/dispatch
# amortization and the capacity ratio embedded in the JSON line, spec
# tokens bitwise vs k=1, strictly fewer decode dispatches
sd = rec["spec_decode"]
assert sd["spec_bitwise_vs_k1"] is True, sd
assert 0 <= rec["spec_acceptance_rate"] <= 1, rec["spec_acceptance_rate"]
assert rec["spec_tokens_per_dispatch"] > 1, rec["spec_tokens_per_dispatch"]
assert sd["spec"]["decode_dispatches"] < sd["k1"]["decode_dispatches"], sd
assert rec["kv_capacity_ratio"] >= 2.0, rec["kv_capacity_ratio"]
assert 0 <= sd["kv_quant"]["token_agreement_vs_fp32"] <= 1, sd
assert "sparkdl_spec_proposed_total" in obs, sorted(obs)
assert "sparkdl_spec_accepted_total" in obs, sorted(obs)
assert "sparkdl_kv_pool_dtype" in obs, sorted(obs)
# ISSUE 13: sequence-parallel long-context prefill — sp axis, shard
# grain, measured speedup (the acceptance bar: sp=2 prefill seconds
# <= 0.75x sp=1, i.e. speedup >= 1.333), bitwise verdict, sp metrics
spf = rec["sp_prefill"]
assert rec["sp_axis"] == 2, rec["sp_axis"]
assert rec["prefill_shard_tokens"] > 0, rec
assert spf["sp_bitwise_vs_sp1"] is True, spf
# the wall-clock bar needs real parallelism: two sp shards on a
# single-core harness just interleave (the PERF.md load-sensitivity
# note), so the 1.333x floor only applies when >=2 CPUs are visible
if (os.cpu_count() or 1) >= 2:
    assert rec["sp_prefill_speedup"] >= 1.333, spf
else:
    assert rec["sp_prefill_speedup"] > 0, spf
assert "sparkdl_sp_ring_steps_total" in obs, sorted(obs)
assert "sparkdl_sp_permute_bytes_total" in obs, sorted(obs)
assert "sparkdl_sp_shard_imbalance" in obs, sorted(obs)
# ISSUE 14: multi-host fabric — the cache-aware router must beat
# round-robin prefix hit rate on the shared-prefix fleet workload,
# with p95s measured for both, and the fabric metric families live
fb = rec["fabric"]
assert rec["fabric_hosts"] == 2, rec["fabric_hosts"]
assert rec["fabric_hit_rate_routed"] > rec["fabric_hit_rate_rr"], fb
assert rec["fabric_hit_rate_routed"] > 0.5, fb
assert rec["fabric_p95_ms_routed"] > 0, fb
assert rec["fabric_p95_ms_rr"] > 0, fb
assert sum(fb["routed"]["routed_per_host"].values()) >= \
    fb["requests_per_round"], fb
assert "sparkdl_fabric_routed_total" in obs, sorted(obs)
assert "sparkdl_fabric_affinity_hits_total" in obs, sorted(obs)
assert "sparkdl_fabric_digest_blocks" in obs, sorted(obs)
# ISSUE 15: elastic autoscaling — the stepped load must produce scale
# events with a visible replica trajectory (up during the burst, back
# down after), SLO burn sampled before/after, and the autoscale metric
# families live on the spine
au = rec["autoscale"]
assert rec["scale_events"] >= 2, rec["scale_events"]
traj = rec["replica_trajectory"]
assert max(traj) >= 2, traj          # the burst scaled the pool up
assert au["replicas_final"] == 1, au  # and the drop scaled it back
sba = rec["slo_burn_before_after"]
assert sba["before"] is not None and sba["after"] is not None, sba
assert au["controller"]["state"] == "ok", au["controller"]
assert "sparkdl_autoscale_decisions_total" in obs, sorted(obs)
assert "sparkdl_autoscale_replicas" in obs, sorted(obs)
assert "sparkdl_autoscale_ticks_total" in obs, sorted(obs)
# ISSUE 16: disaggregated serving — the long-prompt stream must not
# move interactive p95 past the colocated stall (ratio >= 1), the
# split stays bitwise, the int8 handoff moves >= 3.5x fewer bytes
# than fp32, and the handoff metric families are live on the spine
dg = rec["disagg"]
assert dg["long_prompt_len"] >= 3072, dg
assert rec["decode_p95_colocated_vs_disagg"] >= 1.0, dg
assert dg["split_bitwise_vs_colocated"] is True, dg
assert rec["handoff_seconds_p50"] > 0, dg
assert rec["handoff_bytes"]["fp32_over_int8"] >= 3.5, dg
assert dg["handoffs"] >= dg["interactive_requests"], dg
assert "sparkdl_disagg_handoffs_total" in obs, sorted(obs)
assert "sparkdl_disagg_handoff_bytes_total" in obs, sorted(obs)
assert "sparkdl_disagg_handoff_seconds" in obs, sorted(obs)
# ISSUE 17: per-phase latency attribution — all five phases observed
# with non-zero medians, registry-sourced, and the p50s telescope to
# the measured interactive e2e median (generous bound: histogram
# percentiles are bucket-interpolated and the warmup/long-prompt
# crossings ride the same series)
pb = rec["phase_breakdown"]
assert pb is not None, "phase_breakdown missing from disagg artifact"
assert [(r["phase"], r["tier"]) for r in pb["phases"]] == [
    ("queue", "prefill"), ("compute", "prefill"), ("wire", "handoff"),
    ("queue", "decode"), ("compute", "decode")], pb
assert all(r["observations"] > 0 for r in pb["phases"]), pb
assert all(r["p50_s"] > 0 for r in pb["phases"]), pb
assert pb["interactive_p50_s"] > 0, pb
assert abs(pb["sum_p50_s"] - pb["interactive_p50_s"]) <= \
    0.5 * pb["interactive_p50_s"] + 0.05, pb
assert "sparkdl_request_phase_seconds" in obs, sorted(obs)
# ISSUE 18: tiered KV parking — resuming a parked conversation must
# beat re-prefilling its transcript at EVERY swept depth, the host
# tier must hold >= 4x the sessions device HBM alone keeps live, no
# park fell back, and the tier metric families ride the spine
pk = rec["park"]
assert len(pk["depths"]) >= 2, pk
for d in pk["depths"]:
    assert d["turn_resume_p50_ms"] < d["reprefill_p50_ms"], d
    assert d["parked_sessions_per_chip"] >= \
        4 * pk["device_live_sessions"], d
    assert d["tier_blocks"]["host"] > 0, d
    assert d["unparks"] > 0, d
    assert d["park_fallbacks"] == 0, d
assert rec["turn_resume_p50_ms"] < rec["reprefill_p50_ms"], rec
assert rec["parked_sessions_per_chip"] >= \
    4 * pk["device_live_sessions"], rec
assert "sparkdl_kv_tier_blocks" in obs, sorted(obs)
assert "sparkdl_kv_parks_total" in obs, sorted(obs)
assert "sparkdl_kv_unparks_total" in obs, sorted(obs)
# ISSUE 19: scaled router tier — cross-router placement agreement is
# arithmetic (~1.0), steady-state digest deltas move >=10x fewer
# bytes per refresh than the wholesale-forced control at the same
# cadence, N=2 prefix hit rate stays within 10% of single-router,
# p95 measured at both N, and the new families ride the spine
rt = rec["router_tier"]
assert rec["router_agreement_rate"] >= 0.99, rt
assert rec["digest_delta_bytes_per_s"] > 0, rt
assert rec["digest_wholesale_bytes_per_s"] > 0, rt
assert rt["delta_vs_wholesale_per_refresh"] >= 10.0, rt
assert rt["hit_rate_n_vs_1"] >= 0.9, rt
assert rec["router_p95_ms_n1"] > 0, rt
assert rec["router_p95_ms_n"] > 0, rt
assert rt["scaled"]["routers"] >= 2, rt
assert "sparkdl_fabric_digest_delta_bytes_total" in obs, sorted(obs)
assert "sparkdl_fabric_digest_delta_applied_total" in obs, sorted(obs)
assert "sparkdl_fabric_router_dispatch_total" in obs, sorted(obs)
# ISSUE 20: multi-tenant QoS — the worst victim p95 must stay within
# 10% of its flooder-free baseline (and compliance within 10%) while
# the flooder is offered >=3x what its quota admits and its overage
# sheds typed at the door; the driven brownout episode must step the
# ladder to at least shed_background (shedding background submits at
# every raised level) and recover to 0; tenant + overload metric
# families live on the spine
tn = rec["tenancy"]
assert rec["tenant_isolation_ratio"] <= 1.10, tn
assert tn["compliance_ratio"] >= 0.90, tn
fl = tn["storm"]["flooder"]
assert fl["offered"] >= 3 * max(1, fl["admitted"]), fl
assert fl["shed"] > 0, fl
assert 0 < rec["shed_share"] < 1, rec["shed_share"]
assert max(rec["brownout_levels"]) >= 1, rec["brownout_levels"]
assert rec["brownout_levels"][-1] == 0, rec["brownout_levels"]
assert sum(tn["brownout_sheds_per_level"].values()) >= 1, tn
assert "sparkdl_tenant_admitted_total" in obs, sorted(obs)
assert "sparkdl_tenant_shed_total" in obs, sorted(obs)
assert "sparkdl_tenant_latency_seconds" in obs, sorted(obs)
assert "sparkdl_overload_level" in obs, sorted(obs)
assert "sparkdl_overload_transitions_total" in obs, sorted(obs)
assert "sparkdl_overload_shed_total" in obs, sorted(obs)
print("bench_serving contract OK (snapshot + slo + flight + kv + spec "
      "+ sp + fabric + autoscale + disagg + phases + park + router "
      "tier + tenancy embedded)")
'

# Paged-KV smoke (ISSUE 10): (a) a shared-prefix workload through the
# paged engine must hit the prefix cache on >50% of prompt tokens and
# stay BITWISE identical to the dense engine; (b) with a fault plan
# injecting kv.alloc exhaustion, admissions DEFER (no request fails),
# /healthz degrades while the streak lasts, and the flight recorder
# auto-writes a postmortem whose engine context carries the block-pool
# state; (c) peak block usage stays token-bound, far under the dense
# footprint.
FLIGHT_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="kv.alloc:RuntimeError@3*6" \
SPARKDL_TPU_FLIGHT_DIR="$FLIGHT_DIR" python - "$FLIGHT_DIR" <<'EOF'
import glob, json, sys, time
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.observability.flight import flight_recorder, healthz_report
from sparkdl_tpu.serving import ContinuousGPTEngine

flight_recorder().configure(settle_s=0.05, min_interval_s=0.0)
cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
rng = np.random.default_rng(7)
shared = rng.integers(1, cfg.vocab_size, 8).tolist()
cases = [(shared + rng.integers(1, cfg.vocab_size, 3).tolist(), 5)
         for _ in range(8)]

def run(layout):
    eng = ContinuousGPTEngine(
        cfg, variables, n_slots=2, max_len=32, kv_layout=layout,
        kv_block_size=4, prefill_chunk=8, idle_wait_s=0.001)
    futs = [eng.submit(p, n) for p, n in cases]
    outs = [np.asarray(f.result(timeout=60)) for f in futs]
    snap = eng.snapshot()
    eng.close()
    return outs, snap

# (b) first, the fault plan: the 3rd+ allocations fail 6 times -> the
# paged run below defers (streak >= 3 triggers the postmortem) yet
# every request completes
outs_p, snap_p = run("paged")
outs_d, snap_d = run("dense")
assert all(np.array_equal(a, b) for a, b in zip(outs_p, outs_d)), \
    "paged diverged from dense"
kv = snap_p["kv"]
hits, misses = kv["prefix_hits"], kv["prefix_misses"]
hit_rate = hits / (hits + misses)
assert hit_rate > 0.5, (hits, misses)
assert kv["deferrals_total"] >= 3, kv
# (c) token-bound memory: the dense equivalent is n_slots * max_len
# columns; the paged peak is the live requests' worst case
dense_equiv_blocks = 2 * (32 // 4)
assert kv["blocks_used"] < dense_equiv_blocks, kv
# healthz while a streak is LIVE (deterministic manual ticks: a 2-block
# pool, one request holding both, a second deferring): degraded — never
# unhealthy, it self-recovers as the blocker retires
eng = ContinuousGPTEngine(
    cfg, variables, n_slots=2, max_len=32, kv_block_size=16,
    kv_blocks=2, auto_start=False)
blocker = eng.submit([5, 3, 9], 14)  # 17 tokens: the whole pool
eng.tick()
starved = eng.submit([1, 4], 4)
eng.tick(); eng.tick()
assert healthz_report()["status"] == "degraded", healthz_report()
while not (blocker.done() and starved.done()):
    eng.tick()
eng.close()
assert healthz_report()["status"] == "ok", healthz_report()
# (a+b) postmortem written by the exhaustion streak, carrying pool state
time.sleep(0.3)
bundles = glob.glob(sys.argv[1] + "/flight-*.json")
assert bundles, "no postmortem bundle written"
# the FIRST bundle is the fault-plan streak's, written while the
# serving engine was live (later ones may come from the manual-tick
# healthz demo above, whose engine closes before its settle expires)
bundle = json.load(open(sorted(bundles)[0]))
assert bundle["reason"] == "kv.pool_exhausted", bundle["reason"]
ctx_pools = [c.get("kv_pool") for c in bundle["context"].values()
             if isinstance(c, dict) and c.get("kv_pool")]
assert ctx_pools, "bundle context lacks block-pool state"
assert ctx_pools[0]["blocks_total"] > 0, ctx_pools
evs = [e for e in bundle["events"] if e["kind"] == "kv.admission_deferred"]
assert evs, "deferral events missing from the bundle ring"
print(f"paged-KV smoke OK: hit_rate {hit_rate:.2f} > 0.5, bitwise vs "
      f"dense, {kv['deferrals_total']} deferrals -> postmortem with pool "
      f"state, healthz degraded during streak")
EOF
rm -rf "$FLIGHT_DIR"

# Spec-decode smoke (ISSUE 12): (a) k=4 speculative decode must stay
# BITWISE identical to the spec-free engine — including while the env
# fault plan kills two verify dispatches mid-run (spec.verify site:
# the engine falls back to plain decode for those ticks, zero lost
# requests); (b) an injected kv.quantize fault fails the compressed-
# pool build loudly while fp32 builds never hit the site; (c) the int8
# layout fits >= 2x fp32's live tokens in the same pool bytes.
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="spec.verify:RuntimeError@2*2" python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.reliability.faults import inject
from sparkdl_tpu.serving import ContinuousGPTEngine
from sparkdl_tpu.serving.kv_blocks import kv_capacity_ratio

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
cases = [([5, 3, 9, 2, 7], 9), ([6, 8, 6, 1, 6, 8, 6, 1], 10), ([1, 4], 7)]

def run(**kw):
    eng = ContinuousGPTEngine(cfg, variables, n_slots=2, max_len=32,
                              kv_block_size=4, prefill_chunk=8,
                              auto_start=False, **kw)
    futs = [eng.submit(p, n) for p, n in cases]
    while not all(f.done() for f in futs):
        eng.tick()
    eng.close()
    return [np.asarray(f.result(timeout=0)) for f in futs], eng

# the env plan arms spec.verify@2*2: the 2nd and 3rd verify attempts
# fail, those ticks serve plain decode, and the stream must STILL be
# bitwise vs the spec-free engine (which never hits the site)
base, _ = run()
spec, eng = run(spec_k=4)
for a, b in zip(base, spec):
    np.testing.assert_array_equal(a, b)
assert eng._spec_fallbacks == 2, eng._spec_fallbacks
assert eng._spec_dispatches >= 1
assert eng._spec_accepted > 0
with inject("kv.quantize:RuntimeError@1"):
    try:
        run(kv_dtype="int8")
        raise SystemExit("kv.quantize fault did not fail the build")
    except RuntimeError as e:
        assert "kv.quantize" in str(e), e
    run()  # fp32 build never hits the armed site
q, _ = run(kv_dtype="int8")  # compressed pool serves end to end
assert all(len(o) >= 1 for o in q)
assert kv_capacity_ratio(cfg, "int8") >= 2.0
print("spec-decode smoke OK: k=4 bitwise vs k=1 through 2 injected "
      "verify failures (zero lost requests), kv.quantize fails the "
      f"int8 build loudly, int8 fits {kv_capacity_ratio(cfg, 'int8'):.1f}x "
      "fp32 tokens per byte")
EOF

# Tiered-KV park smoke (ISSUE 18): (a) 8 sessions squeezed through a
# device pool holding ~2 live sessions park to the host tier under
# admission pressure (plus a park_cold flush), and every turn-2 resume
# stays BITWISE vs an engine that never parked; (b) the same soak with
# kv.park faults injected mid-run falls back to plain eviction — ZERO
# lost requests, tokens still bitwise, the failures on the flight ring.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel
from sparkdl_tpu.observability.flight import flight_recorder
from sparkdl_tpu.reliability.faults import inject
from sparkdl_tpu.serving import ContinuousGPTEngine

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
kw = dict(n_slots=2, max_len=32, kv_block_size=4, kv_layout="paged",
          idle_wait_s=0.0005)
rng = np.random.default_rng(18)
prompts = [rng.integers(1, cfg.vocab_size, 9).tolist() for _ in range(8)]

def two_turns(eng, park):
    replies = [eng.submit(p, 4).result(timeout=120).tolist()
               for p in prompts]
    if park:
        eng.park_cold()
    outs = [eng.submit(p + r + [5], 4).result(timeout=120).tolist()
            for p, r in zip(prompts, replies)]
    return replies, outs

# (a) pressure-parked sessions resume bitwise vs a roomy untiered pool
eng = ContinuousGPTEngine(cfg, variables, kv_blocks=10,
                          host_kv_blocks=64, **kw)
r_park, o_park = two_turns(eng, park=True)
tiers = eng._kv_snapshot()["tiers"]
assert tiers["parks"] > 0, tiers
assert tiers["unparks"] > 0, tiers
assert tiers["park_fallbacks"] == 0, tiers
eng.close()
ref = ContinuousGPTEngine(cfg, variables, kv_blocks=64, **kw)
r_ref, o_ref = two_turns(ref, park=False)
ref.close()
assert r_park == r_ref and o_park == o_ref, "parked-resume diverged"

# (b) torn parks mid-soak: eviction fallback, zero lost, still bitwise
base = flight_recorder().events_total
eng = ContinuousGPTEngine(cfg, variables, kv_blocks=10,
                          host_kv_blocks=64, **kw)
with inject("kv.park:RuntimeError@2*2"):
    r_chaos, o_chaos = two_turns(eng, park=True)
fb = eng._kv_snapshot()["tiers"]["park_fallbacks"]
assert fb >= 1, fb
eng.close()
assert r_chaos == r_ref and o_chaos == o_ref, "chaos soak diverged"
evs = [e for e in flight_recorder().events()
       if e["kind"] == "kv.park_failed" and e["seq"] > base]
assert evs, "kv.park failure missing from the flight ring"
print(f"tiered-KV park smoke OK: {tiers['parks']} parks / "
      f"{tiers['unparks']} unparks bitwise across 8 sessions on a "
      f"10-block device pool; {fb} torn parks fell back to eviction "
      "with zero lost requests")
EOF

# Multi-tenant QoS smoke (ISSUE 20): one engine under (a) a flooding
# tenant offered ~10x its admission quota — the overage sheds TYPED at
# the door (TenantThrottledError, never a timeout) while every accepted
# request completes; (b) an env-plan tenant.preempt fault on the first
# preemption attempt — the victim still re-queues (zero lost, tokens
# bitwise) and the SECOND attempt preempts clean; (c) a driven brownout
# ladder — level up under synthetic burn (healthz degraded, background
# shed), then recovery back to level 0 with healthz ok.
JAX_PLATFORMS=cpu \
SPARKDL_TPU_FAULT_PLAN="tenant.preempt:RuntimeError@1" python - <<'EOF'
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.models.gpt import GPTConfig, GPTLMHeadModel, generate
from sparkdl_tpu.observability.flight import flight_recorder, healthz_report
from sparkdl_tpu.serving import ContinuousGPTEngine
from sparkdl_tpu.serving.tenancy import (
    PRIORITY_BACKGROUND, BrownoutShedError, OverloadController,
    TenantRegistry, TenantThrottledError, set_process_overload)

cfg = GPTConfig.tiny()
model = GPTLMHeadModel(cfg)
variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
reg = TenantRegistry(latency_threshold_s=5.0)
reg.configure("offline", priority=PRIORITY_BACKGROUND)
reg.configure("flood", rate=5.0, burst=2)
eng = ContinuousGPTEngine(
    cfg, variables, n_slots=1, max_len=32, auto_start=False,
    kv_block_size=4, prefill_chunk=4, tenants=reg)
rng = np.random.default_rng(20)

def oracle(p, n):
    return np.asarray(generate(
        model, variables, jnp.asarray([p], jnp.int32), n)[0, len(p):])

def drain(futs):
    for _ in range(2000):
        eng.tick()
        if all(f.done() for f in futs):
            return
    raise SystemExit("engine never drained")

# (b) two preemption rounds: the env plan tears attempt #1 (victim
# re-queues anyway), attempt #2 preempts clean
base = flight_recorder().events_total
for _ in range(2):
    bg = rng.integers(1, cfg.vocab_size, 12).tolist()
    fg = rng.integers(1, cfg.vocab_size, 6).tolist()
    f_bg = eng.submit(bg, 4, tenant="offline")
    eng.tick()  # first chunk only: mid-prefill, the sole slot held
    f_fg = eng.submit(fg, 4, tenant="acme")
    drain([f_bg, f_fg])  # zero lost, both bitwise
    np.testing.assert_array_equal(f_fg.result(timeout=0), oracle(fg, 4))
    np.testing.assert_array_equal(f_bg.result(timeout=0), oracle(bg, 4))
kinds = [e["kind"] for e in flight_recorder().events()
         if e["seq"] > base and e["kind"].startswith("tenant.")]
assert "tenant.preempt_failed" in kinds, kinds   # round 1: torn
assert "tenant.preempted" in kinds, kinds        # round 2: clean

# (a) flooder storm: 40 offered against a burst-2 bucket; overage shed
# typed, every ACCEPTED request still completes with real tokens
p = rng.integers(1, cfg.vocab_size, 4).tolist()
accepted, shed = [], 0
for _ in range(40):
    try:
        accepted.append(eng.submit(p, 2, tenant="flood"))
    except TenantThrottledError:
        shed += 1
assert shed >= 30, f"flooder only shed {shed}/40"
drain(accepted)
for f in accepted:
    np.testing.assert_array_equal(f.result(timeout=0), oracle(p, 2))
snap = reg.snapshot()["flood"]
assert snap["shed"] == shed and snap["admitted"] == len(accepted), snap

# (c) brownout ladder: hot ticks step it up (healthz degraded,
# background shed at admission), quiet ticks walk it back to 0
ctrl = OverloadController(hysteresis=1, recovery_ticks=1,
                          cooldown_ticks=0)
prev = set_process_overload(ctrl)
try:
    ctrl.evaluate(burn_rate=10.0)
    assert ctrl.level >= 1
    assert healthz_report()["status"] == "degraded", healthz_report()
    try:
        eng.submit(p, 2, tenant="offline")
        raise SystemExit("brownout never shed the background submit")
    except BrownoutShedError as e:
        assert e.level == ctrl.level
    f_ok = eng.submit(p, 2, tenant="acme")  # interactive still admitted
    drain([f_ok])
    ctrl.evaluate(burn_rate=0.0, queue_frac=0.0)
    assert ctrl.level == 0
    assert healthz_report()["status"] == "ok", healthz_report()
finally:
    set_process_overload(prev)
eng.close()
print(f"tenant QoS smoke OK: torn preempt re-queued + clean preempt "
      f"(bitwise both rounds), flooder shed {shed}/40 typed with "
      f"{len(accepted)} accepted all exact, brownout stepped to "
      f"level>=1 (healthz degraded, background shed) and recovered")
EOF

# Fault-injection smoke (ISSUE 5): resumable_finetune survives an
# injected crash at step k and its per-step loss trajectory matches the
# uninterrupted run BITWISE; the disarmed fault_point must stay
# invisible next to a device dispatch (bench-guarded: per-call cost and
# its share of one measured BatchedRunner.run dispatch).
JAX_PLATFORMS=cpu python -c '
import tempfile, time
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.reliability import RetryPolicy, resumable_finetune
from sparkdl_tpu.reliability.faults import fault_point, inject
from sparkdl_tpu.train.finetune import batches_from_arrays, finetune_classifier
from sparkdl_tpu.transformers._inference import BatchedRunner

rng = np.random.default_rng(0)
params = {"w": jnp.asarray(rng.standard_normal((8, 3)) * 0.1, jnp.float32)}
data = {"x": rng.standard_normal((64, 8)).astype(np.float32),
        "labels": rng.integers(0, 3, 64).astype(np.int32)}
mk = lambda: batches_from_arrays(data, batch_size=16, epochs=2, seed=3)
_, base = finetune_classifier(lambda p, x: x @ p["w"], params, mk(),
                              learning_rate=0.1)
with tempfile.TemporaryDirectory() as d, inject("dispatch:RuntimeError@5"):
    _, got = resumable_finetune(
        lambda p, x: x @ p["w"], params, mk, checkpoint_dir=d,
        checkpoint_every=2, learning_rate=0.1,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                          sleep=lambda s: None))
assert [(h["step"], h["loss"], h["accuracy"]) for h in got] == \
    [(h["step"], h["loss"], h["accuracy"]) for h in base]  # bitwise
print("fault-injection smoke OK: crash@5 recovered, trajectory bitwise")

# disarmed overhead guard: per-call cost ~a global load + None test
n = 200_000
t0 = time.perf_counter()
for _ in range(n):
    fault_point("dispatch")
per_call = (time.perf_counter() - t0) / n
assert per_call < 2e-6, f"disarmed fault_point {per_call*1e9:.0f}ns/call"
w = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
r = BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=8,
                  data_parallel=False)
rows = [{"x": rng.standard_normal(8).astype(np.float32)}
        for _ in range(64)]
list(r.run(iter(rows)))  # warm the jit cache
t0 = time.perf_counter()
list(r.run(iter({"x": row["x"]} for row in rows)))
per_dispatch = (time.perf_counter() - t0) / 8
assert per_call / per_dispatch < 0.01, (per_call, per_dispatch)
print(f"fault_point overhead OK: {per_call*1e9:.0f}ns/call disarmed, "
      f"{100*per_call/per_dispatch:.3f}% of one BatchedRunner dispatch")
'
# Quarantine-reintegration smoke (ISSUE 5): a BENCH_REPLICAS=2 pool
# loses one executor mid-load — its riders are re-routed (zero errors),
# the replica is quarantined, and after the executor "restarts" a
# probation probe reintegrates it.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
  BENCH_REPLICAS=2 python -c '
import os, threading, time
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.observability import registry
from sparkdl_tpu.serving import ReplicaPool, ServingEngine
from sparkdl_tpu.transformers._inference import BatchedRunner

n_replicas = int(os.environ["BENCH_REPLICAS"])
w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)),
                jnp.float32)
down = threading.Event()

class Killable:
    def __init__(self, inner, killable):
        self._inner, self._killable = inner, killable
        self.chunk_size = inner.chunk_size
    def run_batch(self, arrays):
        if self._killable and down.is_set():
            raise RuntimeError("executor down")
        return self._inner.run_batch(arrays)

made = []
def make_runner(device):
    r = Killable(BatchedRunner(lambda b: jnp.tanh(b["x"] @ w),
                               batch_size=8, data_parallel=False,
                               device=device), killable=not made)
    made.append(r)
    return r

pool = ReplicaPool(make_runner=make_runner, n_replicas=n_replicas,
                   max_failures=2, probation_s=0.05, probation_max_s=1.0)
pool.warmup({"x": np.zeros((8, 8), np.float32)})
with ServingEngine(pool, max_wait_s=0.002) as eng:
    down.set()  # kill replica 0 mid-load
    futs = [eng.submit({"x": np.full((8,), float(i), np.float32)})
            for i in range(48)]
    for i, f in enumerate(futs):  # every rider re-routed, zero errors
        np.testing.assert_allclose(
            f.result(timeout=60),
            np.tanh(np.full((8,), float(i), np.float32) @ np.asarray(w)),
            rtol=1e-5)
    assert pool.snapshot()["healthy_count"] == n_replicas - 1
    down.clear()  # "restart" the executor; probation probes rejoin it
    deadline = time.monotonic() + 20.0
    while (pool.snapshot()["healthy_count"] < n_replicas
           and time.monotonic() < deadline):
        eng.submit({"x": np.zeros((8,), np.float32)}).result(timeout=60)
        time.sleep(0.02)
    snap = pool.snapshot()
pool.close()
assert snap["healthy_count"] == n_replicas, snap
reint = registry().get("sparkdl_replica_reintegrated_total")
assert reint is not None and reint.snapshot_values().get("", 0) >= 1
print(f"quarantine-reintegration smoke OK: {n_replicas}-replica pool "
      "lost one executor, riders re-routed, replica rejoined via "
      "probation probe")
'

# Flight-recorder chaos smoke (ISSUE 9 acceptance): a fault-plan-injected
# replica failure under load must (a) cost no client a result (re-route),
# (b) quarantine the victim replica, and (c) auto-dump a postmortem
# bundle whose event ring holds the fault injection + the quarantine
# transition and whose trace section holds the re-routed request's FULL
# trace (queue wait, failed replica dispatch, re-routed dispatch,
# terminal request span).
FLIGHT_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
  SPARKDL_TPU_TRACE=1 SPARKDL_TPU_FLIGHT_DIR="$FLIGHT_DIR" \
  SPARKDL_TPU_FAULT_PLAN="replica.execute:RuntimeError@3" python -c '
import glob, json, os, time
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.observability.flight import flight_recorder
from sparkdl_tpu.serving import ReplicaPool, ServingEngine

flight_recorder().configure(settle_s=0.3, min_interval_s=0.0)
w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)),
                jnp.float32)
# probation off: the quarantine must be a stable end state to assert on
pool = ReplicaPool(lambda b: jnp.tanh(b["x"] @ w), batch_size=8,
                   max_failures=1, probation_s=None)
pool.warmup({"x": np.zeros((8, 8), np.float32)})  # site hits 1 and 2
with ServingEngine(pool, max_wait_s=0.002) as eng:
    futs = [eng.submit({"x": np.full((8,), float(i), np.float32)})
            for i in range(48)]
    for i, f in enumerate(futs):  # hit 3 injects; its riders re-route
        np.testing.assert_allclose(
            f.result(timeout=60),
            np.tanh(np.full((8,), float(i), np.float32) @ np.asarray(w)),
            rtol=1e-5)
    assert pool.snapshot()["healthy_count"] == 1, pool.snapshot()
    victim = None
    for f in futs:
        spans = eng.trace(f.request_id)
        failed = [s for s in spans if s["name"] == "serving.replica_batch"
                  and "error" in s["args"]]
        if failed:
            victim = (f.request_id, spans)
            break
    assert victim, "no request trace crossed the injected failure"
    rid, spans = victim
    names = {s["name"] for s in spans}
    assert {"serving.queue_wait", "serving.replica_batch",
            "serving.request"} <= names, names
    # the re-route shows as a SECOND replica dispatch in the same trace
    assert len([s for s in spans
                if s["name"] == "serving.replica_batch"]) >= 2, names
    deadline = time.monotonic() + 15.0
    paths = []
    while not paths and time.monotonic() < deadline:
        paths = glob.glob(os.path.join(
            os.environ["SPARKDL_TPU_FLIGHT_DIR"], "flight-*.json"))
        time.sleep(0.05)
    assert paths, "no postmortem bundle written"
    bundle = json.load(open(sorted(paths)[-1]))
pool.close()
assert bundle["reason"] == "replica_quarantined", bundle["reason"]
events = bundle["events"]
assert any(e["kind"] == "fault.injected"
           and e.get("site") == "replica.execute" for e in events), \
    sorted({e["kind"] for e in events})
assert any(e["kind"] == "replica.quarantined" for e in events)
bundle_spans = {e["args"]["span_id"] for e in bundle["trace_events"]}
missing = [s["name"] for s in spans
           if s["args"]["span_id"] not in bundle_spans]
assert not missing, f"victim trace spans missing from bundle: {missing}"
assert any(p.get("healthy_count") == 1
           for p in bundle["context"].values()
           if isinstance(p, dict) and "healthy_count" in p), \
    "bundle lacks the pool quarantine state"
print(f"flight-recorder chaos smoke OK: injected replica fault -> "
      f"quarantine + postmortem bundle with {len(events)} events, "
      f"victim request {rid} trace ({len(spans)} spans) fully captured")
'
rm -rf "$FLIGHT_DIR"
# Disabled-path overhead guard (ISSUE 9 acceptance): flight-recorder
# append + per-request trace-ID plumbing (tracing OFF) must together
# stay under 1% of one BatchedRunner dispatch.
JAX_PLATFORMS=cpu python -c '
import time
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.flight import flight_recorder
from sparkdl_tpu.transformers._inference import BatchedRunner

assert not tracing.tracing_enabled()
rec = flight_recorder()
n = 200_000
t0 = time.perf_counter()
for _ in range(n):
    rec.record("overhead.guard", site="x")
per_append = (time.perf_counter() - t0) / n
assert per_append < 2e-6, f"flight append {per_append*1e9:.0f}ns/event"
t0 = time.perf_counter()
for _ in range(n):
    rid = tracing.next_request_id()
    tracing.request_context(rid)  # None with tracing off: id is the cost
per_rid = (time.perf_counter() - t0) / n
assert per_rid < 2e-6, f"trace-ID plumbing {per_rid*1e9:.0f}ns/request"
rng = np.random.default_rng(0)
w = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
r = BatchedRunner(lambda b: jnp.tanh(b["x"] @ w), batch_size=8,
                  data_parallel=False)
rows = [{"x": rng.standard_normal(8).astype(np.float32)}
        for _ in range(64)]
list(r.run(iter(rows)))  # warm the jit cache
t0 = time.perf_counter()
list(r.run(iter({"x": row["x"]} for row in rows)))
per_dispatch = (time.perf_counter() - t0) / 8
share = (per_append + per_rid) / per_dispatch
assert share < 0.01, (per_append, per_rid, per_dispatch)
print(f"flight/trace disabled-path overhead OK: append "
      f"{per_append*1e9:.0f}ns + request-id {per_rid*1e9:.0f}ns = "
      f"{100*share:.3f}% of one BatchedRunner dispatch")
'

# Partitioner/ZeRO smoke (ISSUE 6): an fsdp=2 finetune on 2 forced
# virtual CPU devices must (a) measure per-chip optimizer-state bytes
# BELOW the replicated dp baseline (registry gauge
# sparkdl_opt_state_bytes{axis}) and (b) keep the per-step loss
# trajectory at parity with the dp run.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 python -c '
import numpy as np, jax; jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from sparkdl_tpu.observability import registry
from sparkdl_tpu.partition import DataParallelPartitioner, make_mesh
from sparkdl_tpu.train.finetune import batches_from_arrays, finetune_classifier

rng = np.random.default_rng(0)
params = {"w": jnp.asarray(rng.standard_normal((8, 4)) * 0.1, jnp.float32),
          "b": jnp.zeros((4,), jnp.float32)}
data = {"x": rng.standard_normal((64, 8)).astype(np.float32),
        "labels": rng.integers(0, 4, 64).astype(np.int32)}
mk = lambda: batches_from_arrays(data, batch_size=16, epochs=2, seed=3)
apply_fn = lambda p, x: x @ p["w"] + p["b"]

_, base = finetune_classifier(apply_fn, params, mk(), learning_rate=0.1)
zero = DataParallelPartitioner(make_mesh(dp=1, fsdp=2), zero_axis="fsdp")
_, got = finetune_classifier(apply_fn, params, mk(), learning_rate=0.1,
                             partitioner=zero)
bytes_by_axis = registry().get(
    "sparkdl_opt_state_bytes").labelled_values("axis")
assert bytes_by_axis["fsdp"] < bytes_by_axis["replicated"], bytes_by_axis
np.testing.assert_allclose([h["loss"] for h in got],
                           [h["loss"] for h in base], rtol=2e-4)
assert [h["step"] for h in got] == [h["step"] for h in base]
b_sharded, b_repl = bytes_by_axis["fsdp"], bytes_by_axis["replicated"]
print(f"partitioner ZeRO smoke OK: opt-state {b_sharded:.0f}B/chip sharded "
      f"vs {b_repl:.0f}B replicated, fsdp=2 trajectory at parity with dp")
'
# Metrics-endpoint smoke (ISSUE 2): start the exporter the way production
# does (SPARKDL_TPU_METRICS_PORT -> maybe_start_metrics_server), scrape
# once, assert well-formed Prometheus exposition text.
JAX_PLATFORMS=cpu SPARKDL_TPU_METRICS_PORT=0 python -c '
import json, urllib.request
from sparkdl_tpu.observability import maybe_start_metrics_server, registry
from sparkdl_tpu.observability import flight, slo
registry().counter("sparkdl_smoke_total", "endpoint smoke").inc(3)
srv = maybe_start_metrics_server()
assert srv is not None, "SPARKDL_TPU_METRICS_PORT=0 must start the server"
assert maybe_start_metrics_server() is srv, "must be idempotent"
body = urllib.request.urlopen(
    f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read().decode()
assert "# TYPE sparkdl_smoke_total counter" in body, body
assert "sparkdl_smoke_total 3" in body, body
# ISSUE 9 endpoints: /slo.json lists registered trackers, /healthz
# aggregates reliability state, /debug/flight serves a live bundle
tracker = slo.register(slo.SLOTracker(slo.SLO(
    name="smoke", latency_threshold_s=0.1)))
doc = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{srv.port}/slo.json", timeout=5).read())
assert any(s.get("slo") == "smoke" for s in doc["slos"]), doc
slo.unregister(tracker)
hz = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{srv.port}/healthz", timeout=5).read())
assert hz["status"] == "ok" and "retry_budget" in hz, hz
flight.record_event("endpoint.smoke")
fl = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{srv.port}/debug/flight", timeout=5).read())
assert any(e["kind"] == "endpoint.smoke"
           for e in fl["bundle"]["events"]), fl["bundle"]["events"][-3:]
srv.close()
print("metrics endpoint smoke OK (/metrics /slo.json /healthz /debug/flight)")
'
# Autotune smoke (ISSUE 8): a deliberately slow synthetic producer under
# the tuner must reach the throughput of the best hand-picked setting
# within a bounded number of decisions, and a fully pinned run must make
# ZERO tuning decisions.
JAX_PLATFORMS=cpu python -c '
import time
from sparkdl_tpu.ingest import AutoTuner, Pipeline

def slow_fn(x):
    time.sleep(0.003)  # the synthetic bottleneck: 3 ms of host work/item
    return x

def run(parallelism, depth, tuner=None, n=400, tail=120):
    pipe = (Pipeline(range(n), name="smoke")
            .map(slow_fn, parallelism=parallelism, max_parallelism=4,
                 name="work")
            .prefetch(depth, transfer=lambda x: x))
    if tuner is not None:
        pipe.autotune(tuner)
        tuner.start()
    tail_t0 = None
    for i, _ in enumerate(pipe):
        if i == n - tail - 1:
            tail_t0 = time.perf_counter()
    rate = tail / (time.perf_counter() - tail_t0)
    if tuner is not None:
        tuner.stop()
    return rate

# best hand-picked setting: parallelism 4 (the map stage is the
# bottleneck; 4 workers x 3ms ≈ 1333 items/s vs 333 at parallelism 1)
hand = run(parallelism=4, depth=2)

tuned_tuner = AutoTuner(interval_s=0.05, hysteresis=2, cooldown_ticks=1)
tuned = run(parallelism=None, depth=None, tuner=tuned_tuner)
assert tuned_tuner.decision_count >= 1, "tuner never acted on starvation"
assert tuned_tuner.decision_count <= 12, tuned_tuner.decision_count
assert tuned >= 0.6 * hand, (
    f"autotuned steady-state {tuned:.0f}/s < 0.6x hand-tuned {hand:.0f}/s "
    f"after {tuned_tuner.decision_count} decisions")

pinned_tuner = AutoTuner(interval_s=0.05, hysteresis=2, cooldown_ticks=1)
run(parallelism=4, depth=2, tuner=pinned_tuner)  # everything pinned
assert pinned_tuner.decision_count == 0, (
    "pinned knobs moved", pinned_tuner.decision_count)
print(f"autotune smoke OK: hand-tuned {hand:.0f}/s, autotuned "
      f"{tuned:.0f}/s steady-state in {tuned_tuner.decision_count} "
      "decisions; fully pinned run made 0 decisions")
'
# Secondary benches keep the same one-JSON-line contract (values are
# CPU-smoke only; the real numbers come from the chip — PERF.md).
# ISSUE 8: both now embed the autotuner decision count + steady-state
# knob values (registry-sourced) next to the registry snapshot.
for b in bench_tf_ingest.py bench_hostfed.py; do
  JAX_PLATFORMS=cpu BENCH_IMAGES=64 BENCH_BATCH=16 python "$b" | tail -1 | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys(), rec
at = rec["autotune"]
assert isinstance(at["decisions"], int), at
assert isinstance(at["knobs"], dict) and at["knobs"], at
assert "sparkdl_autotune_knob" in rec["observability"], sorted(
    rec["observability"])
print("contract OK:", rec["metric"][:60],
      "autotune:", at["decisions"], "decisions,",
      len(at["knobs"]), "knobs")
'
done

# The driver's EXACT call form: import the module, call dryrun_multichip(8)
# with however many devices this host exposes (1 here — JAX_PLATFORMS=cpu
# without a forced device count), so the self-provisioning re-exec path is
# what gets tested, not an env-prepared shortcut. SPARKDL_TPU_CHAIN_K=2
# pins K=2 for every auto-mode chainer (ISSUE 3): the regimes must all
# still pass with fused dispatch enabled wherever it auto-applies.
JAX_PLATFORMS=cpu SPARKDL_TPU_CHAIN_K=2 python -c 'import __graft_entry__ as g; g.dryrun_multichip(8)'
SDL_SKIP_DRYRUN=1 python __graft_entry__.py
