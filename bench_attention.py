"""Pallas flash-attention hardware proof (VERDICT round-1 next-step #3).

Compiles the fused fwd+bwd kernels on the real chip (interpret=False path
— Mosaic compilation, VMEM budgets and all), asserts bf16-tolerance
correctness against the naive masked-softmax reference ON HARDWARE, and
reports the fwd+bwd speedup at L in {1024, 4096}. Prints ONE JSON line.

Run: python bench_attention.py    (driver-style; TPU under the driver)
"""

from __future__ import annotations

import json
import time

import numpy as np


def scan_time(fn, operands, steps, repeats=3):
    """Per-step time with ``steps`` calls chained INSIDE one jit: a
    ~ms-scale program is invisible under this relay's ~2.4 ms
    per-dispatch overhead and ~70 ms trailing-read RTT, so the benched
    unit is a scan whose device work dwarfs both (PERF.md
    measurement-discipline section): R dispatches of M scanned steps,
    one forced read, minus an explicitly measured empty-dispatch
    baseline. The first-operand perturbation depends on the loop index,
    so XLA cannot CSE the iterations. ``fn(*operands) -> summable``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    first, rest = operands[0], operands[1:]

    @jax.jit
    def many(first, *rest):
        def body(acc, i):
            ff = first + (i * first.dtype.type(1e-8))
            return acc + fn(ff, *rest), None
        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(steps))
        return acc

    @jax.jit
    def trivial(x):
        return x.astype(jnp.float32).ravel()[0]

    float(many(first, *rest))  # compile + drain
    float(trivial(first))
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = many(first, *rest)
    float(out)  # forced scalar read pins the chain
    dt = time.perf_counter() - t0
    # fixed-cost baseline: same dispatch count + trailing read,
    # near-zero device work
    t0 = time.perf_counter()
    for _ in range(repeats):
        z = trivial(first)
    float(z)
    base = time.perf_counter() - t0
    return max(dt - base, 1e-9) / (steps * repeats)


def naive_attention(q, k, v, causal):
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((lq, lk), bool))
        s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def main() -> None:
    import os

    import jax
    import jax.numpy as jnp

    # sitecustomize pre-selects the TPU platform; honor an explicit
    # JAX_PLATFORMS (same contract as bench.py) so CPU smokes stay on CPU.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from sparkdl_tpu.ops.flash_attention import flash_attention

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    interpret = not on_tpu  # compiled Mosaic on hardware — the whole point
    b, h, d = 2, 8, 64
    lengths = (1024, 4096) if on_tpu else (256,)
    steps = 20 if on_tpu else 2

    results = {}
    max_err = 0.0
    for L in lengths:
        rng = np.random.default_rng(L)
        shape = (b, L, h, d)
        q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)

        def flash_loss(q, k, v):
            o = flash_attention(q, k, v, causal=True, interpret=interpret)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def naive_loss(q, k, v):
            return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

        flash_g = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))
        naive_g = jax.jit(jax.grad(naive_loss, argnums=(0, 1, 2)))

        # -- correctness on hardware: fwd + all three grads ---------------
        fo = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=interpret))(q, k, v)
        no = naive_attention(q, k, v, causal=True)
        fwd_err = float(jnp.max(jnp.abs(fo.astype(jnp.float32) - no)))
        gf, gn = flash_g(q, k, v), naive_g(q, k, v)
        bwd_err = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b_.astype(jnp.float32))))
            for a, b_ in zip(gf, gn)
        )
        # bf16 inputs, f32 accumulation: elementwise diffs stay O(bf16 eps)
        # on the O(1)-normalized outputs; grads accumulate over L so allow
        # a scaled tolerance.
        assert fwd_err < 0.05, f"L={L} fwd diverged: {fwd_err}"
        assert bwd_err < 0.5 + 1e-4 * L, f"L={L} bwd diverged: {bwd_err}"
        max_err = max(max_err, fwd_err)

        def grad_step(grad_fn):
            return lambda qq, kk, vv: grad_fn(qq, kk, vv)[0].astype(
                jnp.float32).sum()

        t_flash = scan_time(
            grad_step(jax.grad(flash_loss, argnums=(0, 1, 2))),
            (q, k, v), steps)
        t_naive = scan_time(
            grad_step(jax.grad(naive_loss, argnums=(0, 1, 2))),
            (q, k, v), steps)
        results[L] = {
            "flash_ms": round(t_flash * 1e3, 2),
            "naive_ms": round(t_naive * 1e3, 2),
            "speedup": round(t_naive / t_flash, 2),
        }

    # ---- decode row: single-query cached attention (serving hot loop) --
    from sparkdl_tpu.ops.flash_decode import flash_decode, reference_decode

    Ld = max(lengths)
    # serving-shaped batch, large enough that the dense path's device
    # time clears the dispatch-baseline subtraction noise (bd=8 measured
    # indistinguishable from the empty-dispatch baseline on the chip)
    bd = 64 if on_tpu else 8
    rng = np.random.default_rng(7)
    qd = jnp.asarray(rng.standard_normal((bd, 1, h, d)), jnp.bfloat16)
    ck = jnp.asarray(rng.standard_normal((bd, Ld, h, d)), jnp.bfloat16)
    cv = jnp.asarray(rng.standard_normal((bd, Ld, h, d)), jnp.bfloat16)
    idx = Ld - 1

    err = float(jnp.max(jnp.abs(
        flash_decode(qd, ck, cv, idx, interpret=interpret)
        .astype(jnp.float32)
        - reference_decode(qd, ck, cv, idx).astype(jnp.float32))))
    # same hardware-proof contract as the attention rows: a numerically
    # wrong kernel must fail the bench, not print a speedup
    assert err < 0.05, f"decode diverged: {err}"
    max_err = max(max_err, err)

    t_fd = scan_time(
        lambda q, k_, v_: flash_decode(q, k_, v_, idx,
                                       interpret=interpret)
        .astype(jnp.float32).sum(),
        (qd, ck, cv), steps)
    t_dd = scan_time(
        lambda q, k_, v_: reference_decode(q, k_, v_, idx)
        .astype(jnp.float32).sum(),
        (qd, ck, cv), steps)
    results[f"decode_L{Ld}"] = {
        "flash_ms": round(t_fd * 1e3, 3),
        "dense_ms": round(t_dd * 1e3, 3),
        "speedup": round(t_dd / t_fd, 2),
    }

    # ---- cached-prefill row: prompt Lp into a max_len=Ld buffer --------
    # The dense cached path scores every buffer column (O(max_len) work +
    # a [B,H,Lp,max_len] score tensor in HBM); the flash prefill path
    # (models/gpt.py cached L>1 branch) runs the kernel over the written
    # prefix only — O(Lp).
    Lp = 256 if on_tpu else 32
    qp = jnp.asarray(rng.standard_normal((bd, Lp, h, d)), jnp.bfloat16)

    def dense_prefill(q, ckk, cvv):  # the pre-kernel cached path's math
        qpos = jnp.arange(Lp)
        kpos = jnp.arange(Ld)
        mask = kpos[None, :] <= qpos[:, None]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, ckk,
                       preferred_element_type=jnp.float32) / (d ** 0.5)
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, cvv)

    def flash_prefill(q, ckk, cvv):
        return flash_attention(q, ckk[:, :Lp], cvv[:, :Lp], causal=True,
                               interpret=interpret)

    perr = float(jnp.max(jnp.abs(
        jax.jit(flash_prefill)(qp, ck, cv).astype(jnp.float32)
        - dense_prefill(qp, ck, cv).astype(jnp.float32))))
    assert perr < 0.05, f"prefill diverged: {perr}"
    max_err = max(max_err, perr)
    t_fp = scan_time(
        lambda q, k_, v_: flash_prefill(q, k_, v_)
        .astype(jnp.float32).sum(), (qp, ck, cv), steps)
    t_dp = scan_time(
        lambda q, k_, v_: dense_prefill(q, k_, v_)
        .astype(jnp.float32).sum(), (qp, ck, cv), steps)
    results[f"prefill_L{Lp}_buf{Ld}"] = {
        "flash_ms": round(t_fp * 1e3, 3),
        "dense_ms": round(t_dp * 1e3, 3),
        "speedup": round(t_dp / t_fp, 2),
    }

    # ---- ViT row: flagship vision transformer on this chip -------------
    # ViTB16 featurization throughput plus flash-vs-full on its 197-token
    # attention (VERDICT r4 #7: a flagship family needs a chip number).
    import dataclasses

    from sparkdl_tpu.models.vit import ViTConfig, ViTModel

    vb = 64 if on_tpu else 4
    vit_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    base_cfg = ViTConfig.b16(dtype=vit_dtype)
    xv = jnp.asarray(
        np.random.default_rng(9).standard_normal((vb, 224, 224, 3)),
        vit_dtype)
    variables = ViTModel(
        config=base_cfg, include_top=False, dtype=vit_dtype,
    ).init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3), vit_dtype))
    for impl in ("full", "flash") if on_tpu else ("full",):
        module = ViTModel(
            config=dataclasses.replace(base_cfg, attn_impl=impl),
            include_top=False, dtype=vit_dtype,
        )
        t_v = scan_time(
            lambda x: module.apply(variables, x, train=False)[0]
            .astype(jnp.float32).sum(),
            (xv,), steps if on_tpu else 1)
        results[f"vit_b16_{impl}"] = {
            "ms_per_batch": round(t_v * 1e3, 2),
            "images_per_sec": round(vb / t_v, 1),
        }

    headline = max(lengths)
    print(json.dumps({
        "metric": f"flash-attention fwd+bwd speedup vs naive "
                  f"(L={headline}, {platform}, compiled={not interpret})",
        "value": results[headline]["speedup"],
        "unit": "x",
        "vs_baseline": results[headline]["speedup"],
        "detail": results,
        "max_fwd_abs_err": round(max_err, 4),
    }))


if __name__ == "__main__":
    main()
