"""Pallas flash-attention hardware proof (VERDICT round-1 next-step #3).

Compiles the fused fwd+bwd kernels on the real chip (interpret=False path
— Mosaic compilation, VMEM budgets and all), asserts bf16-tolerance
correctness against the naive masked-softmax reference ON HARDWARE, and
reports the fwd+bwd speedup at L in {1024, 4096}. Prints ONE JSON line.

Run: python bench_attention.py    (driver-style; TPU under the driver)
"""

from __future__ import annotations

import json
import time

import numpy as np


def naive_attention(q, k, v, causal):
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((lq, lk), bool))
        s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def main() -> None:
    import os

    import jax
    import jax.numpy as jnp

    # sitecustomize pre-selects the TPU platform; honor an explicit
    # JAX_PLATFORMS (same contract as bench.py) so CPU smokes stay on CPU.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from sparkdl_tpu.ops.flash_attention import flash_attention

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    interpret = not on_tpu  # compiled Mosaic on hardware — the whole point
    b, h, d = 2, 8, 64
    lengths = (1024, 4096) if on_tpu else (256,)
    steps = 20 if on_tpu else 2

    results = {}
    max_err = 0.0
    for L in lengths:
        rng = np.random.default_rng(L)
        shape = (b, L, h, d)
        q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)

        def flash_loss(q, k, v):
            o = flash_attention(q, k, v, causal=True, interpret=interpret)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def naive_loss(q, k, v):
            return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

        flash_g = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))
        naive_g = jax.jit(jax.grad(naive_loss, argnums=(0, 1, 2)))

        # -- correctness on hardware: fwd + all three grads ---------------
        fo = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=interpret))(q, k, v)
        no = naive_attention(q, k, v, causal=True)
        fwd_err = float(jnp.max(jnp.abs(fo.astype(jnp.float32) - no)))
        gf, gn = flash_g(q, k, v), naive_g(q, k, v)
        bwd_err = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b_.astype(jnp.float32))))
            for a, b_ in zip(gf, gn)
        )
        # bf16 inputs, f32 accumulation: elementwise diffs stay O(bf16 eps)
        # on the O(1)-normalized outputs; grads accumulate over L so allow
        # a scaled tolerance.
        assert fwd_err < 0.05, f"L={L} fwd diverged: {fwd_err}"
        assert bwd_err < 0.5 + 1e-4 * L, f"L={L} bwd diverged: {bwd_err}"
        max_err = max(max_err, fwd_err)

        def timeit(grad_fn):
            """Per-step time with M grad steps chained INSIDE one jit:
            a 3 ms program is invisible under this relay's ~2.4 ms
            per-dispatch overhead and ~70 ms trailing-read RTT, so the
            benched unit is a scan whose device work dwarfs both (PERF.md
            measurement-discipline section): R dispatches of M scanned
            steps, one forced read, minus an explicitly measured
            empty-dispatch baseline. The input perturbation depends on
            the loop index, so XLA cannot CSE the iterations."""
            from jax import lax

            M, R = steps, 3

            @jax.jit
            def many(q, k, v):
                def body(acc, i):
                    qq = q + (i * jnp.bfloat16(1e-8))
                    g = grad_fn(qq, k, v)
                    return acc + g[0].astype(jnp.float32).sum(), None
                acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(M))
                return acc

            @jax.jit
            def trivial(q):
                return q.astype(jnp.float32).ravel()[0]

            float(many(q, k, v))  # compile + drain
            float(trivial(q))
            t0 = time.perf_counter()
            for _ in range(R):
                out = many(q, k, v)
            float(out)  # forced scalar read pins the chain
            dt = time.perf_counter() - t0
            # fixed-cost baseline: same dispatch count + trailing read,
            # near-zero device work
            t0 = time.perf_counter()
            for _ in range(R):
                z = trivial(q)
            float(z)
            base = time.perf_counter() - t0
            return max(dt - base, 1e-9) / (M * R)

        t_flash = timeit(jax.grad(flash_loss, argnums=(0, 1, 2)))
        t_naive = timeit(jax.grad(naive_loss, argnums=(0, 1, 2)))
        results[L] = {
            "flash_ms": round(t_flash * 1e3, 2),
            "naive_ms": round(t_naive * 1e3, 2),
            "speedup": round(t_naive / t_flash, 2),
        }

    headline = max(lengths)
    print(json.dumps({
        "metric": f"flash-attention fwd+bwd speedup vs naive "
                  f"(L={headline}, {platform}, compiled={not interpret})",
        "value": results[headline]["speedup"],
        "unit": "x",
        "vs_baseline": results[headline]["speedup"],
        "detail": results,
        "max_fwd_abs_err": round(max_err, 4),
    }))


if __name__ == "__main__":
    main()
