"""Tier workers: the prefill-only and decode-only engine modes.

Both are thin subclasses of
:class:`~sparkdl_tpu.serving.continuous.ContinuousGPTEngine` — the
split reuses the colocated engine's admission, chunked prefill,
deferral, expiry, and decode machinery wholesale and overrides exactly
the two seams where a phase boundary exists:

* :class:`PrefillWorker` ends a request where decode would begin:
  ``_finish_prefill`` exports the prompt's pool blocks (raw storage —
  int8 pools ship quantized bytes + scales) instead of occupying a
  decode slot, and its Futures resolve to
  :class:`~sparkdl_tpu.disagg.handoff.KVHandoff`. Admission reserves
  PROMPT blocks only (``_admission_budget_tokens`` → 0): the tier's
  pool capacity is spent entirely on prefill concurrency, which is why
  a prefill tier absorbs long prompts without inflating anyone's
  decode latency.
* :class:`DecodeWorker` begins a request where prefill ended:
  ``submit_handoff`` adopts a transferred handoff into the queue
  (already-accepted — depth limits do not re-reject it) and
  ``_admit_handoff`` installs the wire blocks through the engine's own
  quantizing write path, then hands the slot to the untouched decode
  loop. No prompt token is ever re-run on the decode tier.

Failure surfaces are the two fault sites: ``handoff.export`` tears
down like ``_sp_abort`` (blocks released, victim re-queued at the
head, zero loss) and ``handoff.install`` raises the typed
:class:`~sparkdl_tpu.disagg.handoff.HandoffInstallError` the
:class:`~sparkdl_tpu.disagg.PhaseRouter` converts into a prefill-tier
requeue.
"""

from __future__ import annotations

import functools
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from sparkdl_tpu.observability import flight as flight_mod
from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.serving.continuous import ContinuousGPTEngine, _InFlight
from sparkdl_tpu.serving.queue import Request

from sparkdl_tpu.disagg.handoff import (
    _M_HANDOFF_BYTES,
    _M_HANDOFF_SECONDS,
    _M_HANDOFFS,
    HandoffInstallError,
    KVHandoff,
    observe_phase,
)

__all__ = ["DecodeWorker", "PrefillWorker"]


def _require_paged(kwargs: dict, who: str) -> None:
    if kwargs.get("kv_layout", "paged") != "paged":
        raise ValueError(
            f"{who} requires kv_layout='paged': the block pool is the "
            "unit the tier crossing transfers")


class PrefillWorker(ContinuousGPTEngine):
    """A :class:`ContinuousGPTEngine` that ONLY prefills (see module
    docstring). ``submit()`` keeps the colocated signature; the Future
    resolves to a :class:`KVHandoff` instead of generated ids. Chunked
    (and, with ``sp > 1``, sequence-parallel) prefill, prefix caching,
    deferral, and deadline expiry all behave exactly as on the
    colocated engine."""

    def __init__(self, config, variables, **kwargs):
        _require_paged(kwargs, "PrefillWorker")
        auto_start = kwargs.pop("auto_start", True)
        super().__init__(config, variables, auto_start=False, **kwargs)
        import jax

        @jax.jit
        def _export(pool, ids):
            # raw-storage gather: NO dequantize — the wire ships the
            # pool's own bytes (int8 + scales, or fp32/bf16 values), so
            # the decode-side install's requantize round-trips exactly
            k = pool["k"][:, ids]
            v = pool["v"][:, ids]
            if "k_scale" in pool:
                return (k, v, pool["k_scale"][:, ids],
                        pool["v_scale"][:, ids])
            return (k, v)

        self._export_fn = _export
        self._handoffs = 0
        self._export_aborts = 0
        if auto_start:
            self.start()

    def _admission_budget_tokens(self, max_new_tokens: int) -> int:
        # prompt blocks only: the decode tier owns the generation span
        return 0

    def _finish_prefill(self, slot, st, first) -> None:
        """Export instead of decode: package the prompt's pool blocks
        (+ the first decode token the final chunk computed) as a
        :class:`KVHandoff` and resolve the Future with it. The prompt
        stays registered in THIS tier's prefix cache, so a later prompt
        sharing the prefix prefills only its suffix before exporting."""
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.batching import pow2_bucket

        try:
            # the injectable stand-in for a failed export gather or a
            # dead wire: fires BEFORE the prefix registration, so the
            # abort path releases a state identical to _sp_abort's
            fault_point("handoff.export")
        except Exception as e:
            self._export_abort(slot, st, e)
            return
        blocks = st.shared + st.owned
        plen = len(st.prompt)
        nbp = -(-plen // self._kv_bs)
        row = [int(b) for b in blocks[:nbp]]
        # register BEFORE releasing the request's holds: the cache's
        # own hold keeps the prompt blocks alive for prefix reuse
        self._prefix.register(tuple(int(t) for t in st.prompt), row)
        t0 = time.perf_counter()
        with span("disagg.handoff_export", parent=st.req.trace_ctx,
                  request_id=st.req.request_id, slot=slot, blocks=nbp):
            wb = pow2_bucket(nbp, 1, self._mb)
            ids = np.full((wb,), self._pool.sentinel, np.int32)
            ids[:nbp] = row
            out = self._export_fn(self._pool_kv, jnp.asarray(ids))
            # np.asarray forces the gather to COMPLETE before the block
            # references drop below (releasing first would let an
            # eviction + realloc overwrite a block mid-copy)
            out = [np.asarray(x)[:, :nbp] for x in out]
        _M_HANDOFF_SECONDS.observe(time.perf_counter() - t0)
        del self._prefilling[slot]
        self._prefix.release(blocks)
        # phase boundaries (ISSUE 17): the export stamp ends this
        # tier's work; queue/prefill ship as measured DURATIONS so the
        # decode tier can publish all five phases without sharing a
        # clock with us
        exported_at = time.monotonic()
        taken = st.req.taken_at if st.req.taken_at is not None \
            else st.req.enqueued
        h = KVHandoff(
            prompt=st.prompt, max_new_tokens=st.max_new,
            first_token=int(first), kv_dtype=self.kv_dtype,
            block_size=self._kv_bs,
            k=out[0], v=out[1],
            k_scale=out[2] if len(out) == 4 else None,
            v_scale=out[3] if len(out) == 4 else None,
            request_id=st.req.request_id, deadline=st.req.deadline,
            enqueued=st.req.enqueued, trace_ctx=st.req.trace_ctx,
            src_host=self.host_id,
            exported_at=exported_at,
            queue_wait_s=max(0.0, taken - st.req.enqueued),
            prefill_s=max(0.0, exported_at - taken),
            incident_id=flight_mod.current_incident_id())
        self._handoffs += 1
        _M_HANDOFFS.inc(stage="export")
        _M_HANDOFF_BYTES.inc(h.wire_bytes)
        flight_mod.record_event(
            "disagg.handoff_export", request_id=st.req.request_id,
            host=self.host_id, blocks=nbp, bytes=h.wire_bytes)
        now = time.monotonic()
        self._record_request_span(st.req, now, ok=True, tokens=1)
        st.req.future.set_result(h)
        self.metrics.record_request(now - st.req.enqueued, ok=True)

    def _export_abort(self, slot, st, exc: Exception) -> None:
        """An injected ``handoff.export`` fault: tear down exactly like
        ``_sp_abort`` — every block released (staging included), victim
        re-queued at the HEAD (it is owed its place ahead of later
        arrivals), nothing lost. The re-run re-prefills from scratch;
        correctness over the partial work."""
        del self._prefilling[slot]
        self._release_sp_staging(st)
        self._prefix.release(st.all_blocks())
        self._export_aborts += 1
        flight_mod.record_event(
            "disagg.handoff_export_failed",
            request_id=st.req.request_id, host=self.host_id,
            error=type(exc).__name__, prompt_tokens=len(st.prompt))
        self.queue.requeue([st.req])

    def snapshot(self) -> "dict[str, Any]":
        out = super().snapshot()
        out["disagg"] = {"tier": "prefill", "handoffs": self._handoffs,
                         "export_aborts": self._export_aborts}
        return out


class DecodeWorker(ContinuousGPTEngine):
    """A :class:`ContinuousGPTEngine` whose slots start at decode (see
    module docstring). Regular ``submit()`` still works (a decode tier
    can colocate small prompts); ``submit_handoff`` is the cross-tier
    admission surface :class:`~sparkdl_tpu.fabric.host.InProcessHost`
    and the HTTP transport route ``{"handoff": ...}`` payloads to."""

    def __init__(self, config, variables, **kwargs):
        _require_paged(kwargs, "DecodeWorker")
        auto_start = kwargs.pop("auto_start", True)
        super().__init__(config, variables, auto_start=False, **kwargs)
        import jax

        _qw = self._q_write_fn

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _install(pool, kdata, vdata, inst):
            # the same _q_write path as the fused single-device install
            # and the sp handoff: quantized pools quantize HERE — the
            # exact requantize round trip (quantize_kv) that keeps a
            # transferred block bitwise-identical to a local prefill's
            return _qw(pool, (inst,), kdata, vdata)

        self._install_fn = _install
        self._installs = 0
        self._install_faults = 0
        if auto_start:
            self.start()

    # -- cross-tier admission -------------------------------------------------
    def submit_handoff(self, handoff: KVHandoff, *,
                       timeout_s: "float | None" = None) -> Future:
        """Adopt one finished prefill. The Future resolves to generated
        ids exactly like ``submit()``'s would have (first token
        included), so callers cannot tell the phases were split.

        Identity carries over: the handoff's request id IS this
        request's id (one trace end to end), its original enqueue stamp
        feeds latency accounting, and its absolute deadline still
        binds (tightened by ``timeout_s`` if given). The request
        enters via ``queue.adopt`` — already accepted upstream, so the
        depth limit never re-rejects it."""
        h = handoff
        if int(h.block_size) != self._kv_bs:
            raise ValueError(
                f"handoff block_size {h.block_size} != decode tier "
                f"block_size {self._kv_bs}: tiers must agree on the "
                "block geometry")
        plen = len(h.prompt)
        if plen + h.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({h.max_new_tokens})"
                f" exceeds decode-tier max_len {self.max_len}")
        need = -(-(plen + h.max_new_tokens) // self._kv_bs)
        if need > self._pool.n_blocks:
            raise ValueError(
                f"request needs {need} KV blocks; decode-tier pool has "
                f"{self._pool.n_blocks} total — it can never fit")
        deadline = h.deadline
        if timeout_s is not None:
            cap = time.monotonic() + timeout_s
            deadline = cap if deadline is None else min(deadline, cap)
        if h.arrived_at is None:
            # in-process crossing (no from_wire): arrival is now
            h.arrived_at = time.monotonic()
        # postmortem correlation (ISSUE 17): if the prefill tier was
        # mid-incident at export, this tier's next dump joins it
        flight_mod.adopt_incident(h.incident_id)
        rid = int(h.request_id) or tracing.next_request_id()
        fut: Future = Future()
        fut.request_id = rid
        # straight to RUNNING: adopted requests skip take()'s handshake
        # (started=True), and a PENDING Future could be cancelled out
        # from under the install
        fut.set_running_or_notify_cancel()
        req = Request(
            h, fut, deadline,
            h.enqueued if h.enqueued else time.monotonic(),
            trace_ctx=(h.trace_ctx if h.trace_ctx is not None
                       else tracing.request_context(rid)),
            request_id=rid,
            started=True)
        self.queue.adopt(req)
        return fut

    def _admit(self, slot: int, req: Request) -> bool:
        if isinstance(req.payload, KVHandoff):
            return self._admit_handoff(slot, req)
        return super()._admit(slot, req)

    def _admit_handoff(self, slot: int, req: Request) -> bool:
        """Install a transferred handoff into this tier's pool and
        start decode with NO re-prefill. Mirrors ``_admit_paged`` +
        ``_finish_prefill``: longest-prefix match first (full blocks
        only — the wire carries every block whole, so a partial-tail
        COW copy buys nothing), worst-case allocation under the same
        deferral protocol, install through the shared quantizing write
        path, then prefix registration so the transferred prompt is
        shareable on THIS tier too. Returns False on pool exhaustion
        (caller defers — the handoff duck-types GenRequest). Raises
        :class:`HandoffInstallError` when the ``handoff.install`` site
        fires — a request-level error the PhaseRouter answers with a
        prefill-tier requeue."""
        import jax.numpy as jnp

        try:
            fault_point("handoff.install")
        except Exception as e:
            self._install_faults += 1
            flight_mod.record_event(
                "disagg.handoff_install_failed",
                request_id=req.request_id, host=self.host_id,
                error=type(e).__name__)
            raise HandoffInstallError(
                f"KV handoff install failed on host {self.host_id}: "
                f"{e!r}") from e
        h: KVHandoff = req.payload
        prompt = np.asarray(h.prompt, np.int32)
        plen = len(prompt)
        toks = tuple(int(t) for t in prompt)
        nbp = -(-plen // self._kv_bs)
        nb_total = -(-(plen + h.max_new_tokens) // self._kv_bs)
        m = self._prefix.match(toks[:-1])
        if m.partial_block is not None:
            # full blocks only (see docstring): drop the partial hold
            self._prefix.release([m.partial_block])
        shared = m.full_blocks
        n_shared = len(shared)
        try:
            owned = self._alloc_blocks(nb_total - n_shared)
        except Exception as e:
            # an injected kv.alloc fault is exhaustion here too: defer,
            # never fail the transferred request
            flight_mod.record_event(
                "kv.alloc_error", error=type(e).__name__,
                request_id=req.request_id)
            owned = None
        if owned is None:
            self._prefix.release(shared)
            self._defer_pool = self._pool
            return False
        # commit point: blocks are allocated, the install WILL run.
        # Everything before this stamp is decode-queue time; everything
        # after (install + decode loop) is decode-compute time.
        t_adm = time.monotonic()
        self._prefix.record_lookup(m.hit_tokens, plen - m.hit_tokens)
        if m.hit_tokens:
            flight_mod.record_event(
                "kv.prefix_hit", request_id=req.request_id,
                hit_tokens=m.hit_tokens, prompt_tokens=plen)
        # install targets: owned blocks at the non-shared prompt
        # positions; sentinel at shared positions (their content is the
        # cached blocks') and past the prompt (decode writes those)
        inst = np.full((self._mb,), self._pool.sentinel, np.int32)
        inst[n_shared:nbp] = owned[:nbp - n_shared]
        kdata, vdata = self._wire_to_compute(h)
        t0 = time.perf_counter()
        with span("disagg.handoff_install", parent=req.trace_ctx,
                  request_id=req.request_id, slot=slot, blocks=nbp,
                  shared_blocks=n_shared):
            self._pool_kv = self._install_fn(
                self._pool_kv, kdata, vdata, jnp.asarray(inst))
        _M_HANDOFF_SECONDS.observe(time.perf_counter() - t0)
        _M_HANDOFFS.inc(stage="install")
        self._installs += 1
        row = np.full((self._mb,), self._pool.sentinel, np.int32)
        row[:n_shared] = shared
        row[n_shared:nb_total] = owned
        self._table[slot] = row
        self._prefix.register(toks, [int(b) for b in row[:nbp]])
        self._pidx[slot] = plen
        self._last_tok[slot] = int(h.first_token)
        fl = _InFlight(req, [int(h.first_token)], h.max_new_tokens,
                       blocks=shared + owned, prompt=prompt)
        self._inflight[slot] = fl
        self._pool.reset_deferral_streak()
        # latency attribution (ISSUE 17): this is the single place all
        # five request phases publish from — the prefill tier shipped
        # its two as measured durations; wire/queue/compute are local
        # stamps on THIS clock. fl carries the admit stamp so
        # _complete() can close the (compute, decode) phase.
        arrived = h.arrived_at if h.arrived_at is not None else t_adm
        observe_phase("queue", "prefill", h.queue_wait_s)
        observe_phase("compute", "prefill", h.prefill_s)
        if h.exported_at is not None:
            wire_s = max(0.0, arrived - h.exported_at)
            observe_phase("wire", "handoff", wire_s)
            # the wire crossing as a span: recorded retroactively on
            # the DECODE host (re-anchored export stamp → install end),
            # parented into the request's one fleet-wide trace
            tracing.record_span(
                "handoff.wire", h.exported_at, time.monotonic(),
                parent=req.trace_ctx, request_id=req.request_id,
                src_host=h.src_host, dst_host=self.host_id,
                bytes=h.wire_bytes, wire_s=wire_s,
                decode_queue_s=max(0.0, t_adm - arrived),
                # the prefill tier's measured durations ride along so
                # fleet stitching reads ALL five phases off this one
                # span (stitch_phase_breakdown)
                queue_wait_s=float(h.queue_wait_s),
                prefill_s=float(h.prefill_s))
        observe_phase("queue", "decode", max(0.0, t_adm - arrived))
        fl._phase_admit_start = t_adm
        flight_mod.record_event(
            "disagg.handoff_installed", request_id=req.request_id,
            host=self.host_id, blocks=nbp, shared_blocks=n_shared,
            src_host=h.src_host)
        if self._is_done(fl):  # max_new_tokens=1, or instant eos
            self._complete(slot)
        return True

    def _complete(self, slot: int) -> None:
        # close the (compute, decode) phase for adopted handoffs: the
        # admit stamp rides the _InFlight (dies with it — failure-safe)
        fl = self._inflight.get(slot)
        t_adm = getattr(fl, "_phase_admit_start", None)
        if t_adm is not None:
            observe_phase("compute", "decode",
                          time.monotonic() - t_adm)
        super()._complete(slot)

    def _wire_to_compute(self, h: KVHandoff):
        """Wire storage → install-ready fp32 block data, padded to the
        table width (the pad lands on sentinel targets and drops).
        int8 wire dequantizes exactly (``q·s``); since the wire values
        ORIGINATED from the storage dtype, every downstream cast or
        requantize round-trips exactly — transferred blocks land
        bitwise-identical to locally prefilled ones."""
        k = np.asarray(h.k)
        v = np.asarray(h.v)
        if h.k_scale is not None:
            k = (k.astype(np.float32)
                 * np.asarray(h.k_scale, np.float32)[..., None, None])
            v = (v.astype(np.float32)
                 * np.asarray(h.v_scale, np.float32)[..., None, None])
        else:
            k = k.astype(np.float32)
            v = v.astype(np.float32)
        pad = self._mb - k.shape[1]
        if pad > 0:
            ps = (k.shape[0], pad) + k.shape[2:]
            k = np.concatenate([k, np.zeros(ps, k.dtype)], axis=1)
            v = np.concatenate([v, np.zeros(ps, v.dtype)], axis=1)
        return k, v

    def snapshot(self) -> "dict[str, Any]":
        out = super().snapshot()
        out["disagg"] = {"tier": "decode", "installs": self._installs,
                         "install_faults": self._install_faults}
        return out
