"""Disaggregated prefill/decode serving (ISSUE 16).

Prefill and decode are different workloads sharing one engine only by
historical accident: prefill is bursty, compute-bound, and brief;
decode is steady, memory-bound, and long-lived. Colocated, a 3072-token
prompt's chunked prefill shares every engine tick with live decode —
interactive p95 pays for throughput traffic. This package splits them
into independently scaled tiers connected by one quantized KV-block
transfer per request:

* :class:`PrefillWorker` — prefill-only engine mode; Futures resolve
  to a :class:`KVHandoff` (the prompt's pool blocks in RAW storage —
  int8 pools ship ~4× fewer wire bytes than fp32 — plus the first
  decode token).
* :class:`DecodeWorker` — decode-tier engine mode; ``submit_handoff``
  installs transferred blocks through the engine's own quantizing
  write path and starts decode with no re-prefill. Greedy tokens stay
  bitwise-identical to the colocated engine's.
* :class:`PhaseRouter` — per-phase placement (prefill: queue depth /
  affinity; decode: slot + KV headroom) and the cross-tier zero-loss
  contract: a handoff lost mid-crossing re-queues at the prefill
  tier's queue head.
* :func:`tier_autoscalers` — each tier scales on its own signal
  (prefill: queue depth; decode: occupancy + KV exhaustion).
* :class:`BatchPrefillFiller` — offline work soaks idle prefill
  capacity, preempted by interactive arrivals.
"""

from sparkdl_tpu.disagg.filler import BatchPrefillFiller
from sparkdl_tpu.disagg.handoff import HandoffInstallError, KVHandoff
from sparkdl_tpu.disagg.phase_router import PhaseRouter
from sparkdl_tpu.disagg.scaling import (
    decode_tier_signals,
    prefill_tier_signals,
    tier_autoscalers,
)
from sparkdl_tpu.disagg.workers import DecodeWorker, PrefillWorker

__all__ = [
    "BatchPrefillFiller",
    "DecodeWorker",
    "HandoffInstallError",
    "KVHandoff",
    "PhaseRouter",
    "PrefillWorker",
    "decode_tier_signals",
    "prefill_tier_signals",
    "tier_autoscalers",
]
