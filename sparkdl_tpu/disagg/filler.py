"""Batch-prefill filler: offline work rides the idle prefill tier.

A prefill tier sized for interactive bursts is idle most of the time —
bursts are bursts. :class:`BatchPrefillFiller` soaks that idle capacity
with background-priority offline requests (batch scoring, evaluation
sweeps) under one hard rule: **offline work never delays a live
prompt.** Admission checks the tier's LIVE queue depth immediately
before every submit and stands down the moment any interactive work is
queued; at most ``max_inflight`` offline requests are outstanding, so
a returning burst waits behind at most that many already-started
prefills (each bounded by one chunked prefill, not a decode span).

``pump()`` is the deterministic single-step form tests drive;
:meth:`start` runs it on a daemon thread at ``interval_s`` cadence.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Iterator

from sparkdl_tpu.observability import flight
from sparkdl_tpu.serving import tenancy

__all__ = ["BatchPrefillFiller"]


class BatchPrefillFiller:
    """Feed ``source`` — an iterable of ``(prompt_ids,
    max_new_tokens)`` pairs — through ``phase_router`` whenever the
    prefill tier is idle. Results (generated-id arrays) land on
    ``on_result(result)`` if given, else collect on :attr:`results`;
    failures count on :attr:`failed` and never retry (offline work is
    re-runnable by nature — the zero-loss contract is for ACCEPTED
    interactive traffic)."""

    def __init__(self, phase_router, source: "Iterable[tuple]", *,
                 max_inflight: int = 2, interval_s: float = 0.02,
                 on_result: "Callable[[Any], None] | None" = None,
                 tenant: str = "offline",
                 priority: int = tenancy.PRIORITY_BACKGROUND):
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}")
        #: offline work rides the LOWEST priority class on the shared
        #: per-tenant scheduler (ISSUE 20): an interactive arrival is
        #: always served first, and may preempt an offline prefill
        #: between chunks — the filler's own stand-down checks are now
        #: the polite fast path, not the only protection
        self.tenant = tenant
        self.priority = int(priority)
        self.phase_router = phase_router
        self._source: Iterator = iter(source)
        self.max_inflight = max_inflight
        self.interval_s = interval_s
        self._on_result = on_result
        self.results: "list[Any]" = []
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self._outstanding = 0
        self._pending: "tuple | None" = None
        self._source_dry = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- one deterministic step ----------------------------------------------
    def pump(self) -> int:
        """Admit as many offline requests as idle capacity allows RIGHT
        NOW; returns how many were submitted. Zero whenever the prefill
        tier has queued work (interactive traffic first) or
        ``max_inflight`` offline requests are already out."""
        n = 0
        while True:
            with self._lock:
                if self._outstanding >= self.max_inflight:
                    return n
                if self._source_dry and self._pending is None:
                    return n
            if tenancy.overload_level() >= tenancy.LEVEL_SHED_BACKGROUND:
                return n  # brownout: offline load is the first shed
            if self.phase_router.tier_depths()["prefill"] > 0:
                return n  # live prompts queued: stand down
            item = self._next_item()
            if item is None:
                return n
            prompt, max_new = item
            try:
                fut = self.phase_router.submit(
                    prompt, max_new,
                    tenant=self.tenant, priority=self.priority)
            except Exception:
                # tier refused (closing/overloaded): hold the item and
                # retry on a later pump — the source is not consumed
                with self._lock:
                    self._pending = item
                return n
            with self._lock:
                self._outstanding += 1
                self.submitted += 1
            fut.add_done_callback(self._done)
            n += 1

    def _next_item(self) -> "tuple | None":
        with self._lock:
            if self._pending is not None:
                item, self._pending = self._pending, None
                return item
            if self._source_dry:
                return None
        try:
            return next(self._source)
        except StopIteration:
            with self._lock:
                self._source_dry = True
            return None

    def _done(self, fut) -> None:
        failed = fut.cancelled() or fut.exception() is not None
        with self._lock:
            self._outstanding -= 1
            if failed:
                self.failed += 1
            else:
                self.completed += 1
        if failed:
            flight.record_event(
                "disagg.filler_request_failed",
                error=(type(fut.exception()).__name__
                       if not fut.cancelled() else "CancelledError"))
            return
        res = fut.result()
        if self._on_result is not None:
            self._on_result(res)
        else:
            self.results.append(res)

    @property
    def drained(self) -> bool:
        """True once the source is exhausted and nothing is in flight."""
        with self._lock:
            return (self._source_dry and self._pending is None
                    and self._outstanding == 0)

    # -- cadence thread -------------------------------------------------------
    def start(self) -> "BatchPrefillFiller":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            stop = self._stop = threading.Event()
        t = threading.Thread(
            target=self._run, args=(stop,),
            name="sparkdl-disagg-filler", daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            if self.drained:
                return
            self.pump()
            stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
