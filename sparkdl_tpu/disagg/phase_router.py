"""Phase routing: prefill placement and decode placement are different
problems, so each tier gets its own :class:`~sparkdl_tpu.fabric.router.Router`.

Prefill is bursty and compute-bound — its router scores on queue depth
(and prompt affinity, so shared prefixes keep landing where their
blocks are cached). Decode is steady and memory-bound — its router
runs the ``headroom`` policy (free slots × KV availability), because a
decode host with slots but no blocks is not headroom at all.

:meth:`PhaseRouter.submit` chains the two: place prefill → Future of a
:class:`~sparkdl_tpu.disagg.handoff.KVHandoff` → place the handoff on
the decode tier → the caller's one Future of generated ids. The chain
is callback-driven (no thread parks per request).

**The zero-loss contract crosses tiers.** Each inner Router already
covers failures within its tier (drain/requeue, host-level failover).
The new surface is the crossing itself: a decode-side
:class:`~sparkdl_tpu.disagg.handoff.HandoffInstallError` — or a decode
tier whose failover options ran out mid-handoff — re-queues the victim
at the PREFILL tier's queue head via :meth:`Router.requeue`, identity
intact (request id, trace, original enqueue stamp, absolute deadline),
ahead of later arrivals. Bounded by ``max_handoff_retries``; an
accepted request is only ever lost to its own deadline.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any

import numpy as np

from sparkdl_tpu.fabric.host import HOST_LEVEL_ERRORS
from sparkdl_tpu.fabric.router import Router
from sparkdl_tpu.observability import flight
from sparkdl_tpu.serving.continuous import GenRequest
from sparkdl_tpu.serving.queue import Request

from sparkdl_tpu.disagg.handoff import (
    _M_TIER_DEPTH,
    HandoffInstallError,
    KVHandoff,
)

__all__ = ["PhaseRouter"]

#: Errors that re-queue the victim at the prefill tier: the typed
#: install failure, plus a decode tier that lost the request at the
#: host level after the inner router exhausted its failover options.
_REQUEUE_ERRORS = (HandoffInstallError,) + HOST_LEVEL_ERRORS


class PhaseRouter:
    """Route requests across a prefill tier and a decode tier (see
    module docstring). ``prefill_hosts``/``decode_hosts`` are iterables
    of :class:`~sparkdl_tpu.fabric.host.HostHandle`; extra
    ``router_kwargs`` reach both inner Routers."""

    def __init__(self, prefill_hosts, decode_hosts, *,
                 prefill_policy: str = "affinity",
                 decode_policy: str = "headroom",
                 max_handoff_retries: int = 2,
                 **router_kwargs):
        if max_handoff_retries < 0:
            raise ValueError(
                f"max_handoff_retries must be >= 0, got "
                f"{max_handoff_retries}")
        self.max_handoff_retries = max_handoff_retries
        self.prefill = Router(prefill_hosts, policy=prefill_policy,
                              **router_kwargs)
        try:
            self.decode = Router(decode_hosts, policy=decode_policy,
                                 **router_kwargs)
        except BaseException:
            self.prefill.close()
            raise
        self._lock = threading.Lock()
        self._closed = False
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.requeues = 0
        flight.record_event(
            "disagg.phase_router_start",
            prefill_hosts=len(self.prefill.hosts()),
            decode_hosts=len(self.decode.hosts()))
        # context provider LAST: everything it reads exists by now
        self._flight_name = f"disagg-phase-router-{id(self):x}"
        flight.add_context_provider(self._flight_name, self.snapshot)

    # -- submission -----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               timeout_s: "float | None" = None,
               session: "str | None" = None,
               tenant: str = "default",
               priority: "int | None" = None) -> Future:
        """One Future of the generated ids (first token included) —
        indistinguishable from a colocated engine's ``submit``, except
        the prompt prefilled on one tier and decodes on another.
        ``tenant``/``priority`` ride the payload onto the prefill
        tier's per-tenant scheduler (ISSUE 20) and survive a mid-
        handoff requeue — a background victim re-enters its own class,
        never ahead of interactive work."""
        if self._closed:
            raise RuntimeError("PhaseRouter is closed")
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        caller: Future = Future()
        caller.set_running_or_notify_cancel()
        with self._lock:
            self.submitted += 1
        self._start_prefill(prompt_ids, max_new_tokens, caller,
                            deadline, session,
                            self.max_handoff_retries,
                            tenant, priority)
        return caller

    @staticmethod
    def _remaining(deadline: "float | None") -> "float | None":
        if deadline is None:
            return None
        # floor just above zero: the tier engines expire it properly,
        # where a negative timeout would be a submit-time ValueError
        return max(1e-3, deadline - time.monotonic())

    def _start_prefill(self, prompt, max_new, caller, deadline,
                       session, retries_left,
                       tenant: str = "default",
                       priority: "int | None" = None) -> None:
        try:
            fut = self.prefill.submit(
                {"prompt": prompt, "max_new_tokens": max_new,
                 "tenant": tenant, "priority": priority},
                timeout_s=self._remaining(deadline), session=session)
        except Exception as e:
            self._finish(caller, exc=e)
            return
        fut.add_done_callback(lambda f: self._on_prefill_done(
            f, caller, deadline, session, retries_left,
            tenant, priority))

    def _on_prefill_done(self, f: Future, caller, deadline, session,
                         retries_left, tenant: str = "default",
                         priority: "int | None" = None) -> None:
        try:
            handoff = f.result()
        except BaseException as e:
            # the prefill Router already burned its own failover
            # options; what reaches here is the request's outcome
            self._finish(caller, exc=e)
            return
        self._start_decode(handoff, caller, deadline, session,
                           retries_left, tenant, priority)

    def _start_decode(self, h: KVHandoff, caller, deadline, session,
                      retries_left, tenant: str = "default",
                      priority: "int | None" = None) -> None:
        try:
            fut = self.decode.submit(
                {"handoff": h}, timeout_s=self._remaining(deadline))
        except Exception as e:
            self._lost_mid_handoff(e, h, caller, deadline, session,
                                   retries_left, tenant, priority)
            return
        fut.add_done_callback(lambda f: self._on_decode_done(
            f, h, caller, deadline, session, retries_left,
            tenant, priority))

    def _on_decode_done(self, f: Future, h, caller, deadline, session,
                        retries_left, tenant: str = "default",
                        priority: "int | None" = None) -> None:
        try:
            self._finish(caller, result=f.result())
        except BaseException as e:
            self._lost_mid_handoff(e, h, caller, deadline, session,
                                   retries_left, tenant, priority)

    def _lost_mid_handoff(self, exc, h, caller, deadline, session,
                          retries_left, tenant: str = "default",
                          priority: "int | None" = None) -> None:
        """The handoff died between tiers. Retryable losses re-enter at
        the prefill tier's queue HEAD; anything else is the request's
        own outcome."""
        if (not isinstance(exc, _REQUEUE_ERRORS)
                or retries_left <= 0 or self._closed):
            self._finish(caller, exc=exc)
            return
        self._requeue_at_prefill(exc, h, caller, deadline, session,
                                 retries_left - 1, tenant, priority)

    def _requeue_at_prefill(self, exc, h: KVHandoff, caller, deadline,
                            session, retries_left,
                            tenant: str = "default",
                            priority: "int | None" = None) -> None:
        """The zero-loss crossing: rebuild the victim as an
        already-accepted :class:`Request` — request id, trace context,
        original enqueue stamp, and absolute deadline all preserved —
        and hand it to :meth:`Router.requeue`, which places it at a
        surviving prefill host's queue head: the victim re-prefills
        AHEAD of requests that arrived after it."""
        with self._lock:
            self.requeues += 1
        flight.record_event(
            "disagg.handoff_requeued", request_id=h.request_id,
            error=type(exc).__name__, retries_left=retries_left)
        # postmortem trigger (ISSUE 17): a lost crossing is an incident
        # — the dump's incident_id joins this tier's bundle with the
        # prefill tier's (the id rode the handoff wire, or mints here
        # and rides the NEXT export within the TTL)
        flight.trigger_dump(
            "disagg.handoff_lost", request_id=h.request_id,
            error=type(exc).__name__, src_host=h.src_host)
        inner: Future = Future()
        inner.request_id = h.request_id
        inner.set_running_or_notify_cancel()
        from sparkdl_tpu.serving import tenancy

        req = Request(
            GenRequest(np.asarray(h.prompt, np.int32),
                       int(h.max_new_tokens)),
            inner,
            deadline if deadline is not None else h.deadline,
            h.enqueued if h.enqueued else time.monotonic(),
            trace_ctx=h.trace_ctx,
            request_id=int(h.request_id),
            started=True,
            tenant=tenant,
            priority=(priority if priority is not None
                      else tenancy.PRIORITY_INTERACTIVE))
        inner.add_done_callback(lambda f: self._on_prefill_done(
            f, caller, deadline, session, retries_left,
            tenant, priority))
        try:
            self.prefill.requeue([req])
        except Exception as e:
            # requeue itself failing resolves inner (or nothing took
            # the request): make sure the caller hears SOMETHING
            if not inner.done():
                self._finish(caller, exc=e)

    def _finish(self, caller: Future, *, result=None,
                exc: "BaseException | None" = None) -> None:
        with self._lock:
            if exc is None:
                self.completed += 1
            else:
                self.failed += 1
        try:
            if exc is not None:
                caller.set_exception(exc)
            else:
                caller.set_result(result)
        except InvalidStateError:
            pass  # already resolved (e.g. double failure report)

    # -- introspection / lifecycle --------------------------------------------
    def tier_depths(self) -> "dict[str, int]":
        """Live queued-request count per tier (and the
        ``sparkdl_disagg_tier_depth`` gauge publication point)."""
        out = {}
        for tier, router in (("prefill", self.prefill),
                             ("decode", self.decode)):
            depth = 0
            for handle in router.host_handles():
                try:
                    depth += int(
                        handle.capacity().get("queue_depth") or 0)
                except Exception:
                    continue  # a dead host holds no queue
            out[tier] = depth
            _M_TIER_DEPTH.set(depth, tier=tier)
        return out

    def refresh(self) -> None:
        """Manual host-state refresh for both tiers (tests run with
        ``auto_refresh=False``); also republishes the depth gauges."""
        self.prefill.refresh()
        self.decode.refresh()
        self.tier_depths()

    def snapshot(self) -> "dict[str, Any]":
        with self._lock:
            counts = {"submitted": self.submitted,
                      "completed": self.completed,
                      "failed": self.failed,
                      "requeues": self.requeues}
        return {"disagg": {
            **counts,
            "prefill_hosts": len(self.prefill.hosts()),
            "decode_hosts": len(self.decode.hosts()),
            "prefill": self.prefill.snapshot(),
            "decode": self.decode.snapshot(),
        }}

    def close(self) -> None:
        """Stop both inner routers. Hosts are NOT closed — the caller
        owns their lifecycle (same contract as :meth:`Router.close`)."""
        if self._closed:
            return
        self._closed = True
        flight.remove_context_provider(self._flight_name)
        try:
            self.prefill.close()
        finally:
            self.decode.close()

    def __enter__(self) -> "PhaseRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
