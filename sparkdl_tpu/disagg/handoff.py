"""Quantized KV-block handoff between serving tiers (ISSUE 16).

The unit of work a disaggregated fabric moves is not a request — it is
a FINISHED PREFILL: the prompt's KV blocks plus the one token the final
prefill chunk produced. :class:`KVHandoff` packages exactly that, in
the pool's RAW storage layout, so the tier crossing inherits the
quantized pool's wire economics for free:

* an ``int8`` pool ships ``int8`` values plus one fp32 scale per
  written column (``models.gpt.quantize_kv``'s layout) — per token that
  is ``2·hidden + 8`` bytes against fp32's ``8·hidden``, a
  ``4/(1 + 4/hidden)``× reduction (3.56× at hidden=32, →4× as hidden
  grows);
* the decode-side install dequantizes (``q·s``, exact) and rides the
  engine's shared ``_q_write`` path, whose requantize is the exact
  round trip ``quantize_kv`` documents (absmax maps to ±127) — so a
  transferred block is BITWISE-identical to one the decode host would
  have prefilled itself, and greedy tokens cannot drift across the
  split.

Identity crosses with the data: ``request_id`` (= trace id, fleet-unique
since ISSUE 17), the serialized trace ``SpanContext`` (so decode-tier
spans parent into the SAME trace the prefill tier started), the live
flight-recorder ``incident_id`` if any (so both tiers' postmortem
bundles join on one incident), the absolute deadline (re-anchored as
remaining seconds over the HTTP transport — monotonic clocks do not
cross processes; the export stamp re-anchors the same way, as elapsed
age), and the original enqueue stamp, so latency accounting and the
zero-loss requeue contract see ONE request end to end. The prefill
tier's measured ``queue_wait_s``/``prefill_s`` ship as DURATIONS (clock-
safe), feeding the decode-side per-request phase attribution
(``sparkdl_request_phase_seconds{phase,tier}``). The object duck-types
:class:`~sparkdl_tpu.serving.continuous.GenRequest`
(``.prompt``/``.max_new_tokens``), so the decode engine's deferral path
treats an adopted handoff like any admitted request.
"""

from __future__ import annotations

import base64
import dataclasses
import time
from typing import Any

import numpy as np

from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.registry import registry

__all__ = ["HandoffInstallError", "KVHandoff", "observe_phase"]

_M_HANDOFFS = registry().counter(
    "sparkdl_disagg_handoffs_total",
    "KV-block handoffs between serving tiers, by stage (export = "
    "prefill-side gather+package complete; install = decode-side "
    "blocks installed, decode started without re-prefill)",
    labels=("stage",))
_M_HANDOFF_BYTES = registry().counter(
    "sparkdl_disagg_handoff_bytes_total",
    "K/V payload bytes exported on the tier-crossing wire (int8 pools "
    "ship quantized values + per-column scales — ~4x fewer bytes than "
    "fp32 at serving hidden sizes)")
_M_HANDOFF_SECONDS = registry().histogram(
    "sparkdl_disagg_handoff_seconds",
    "per-stage handoff cost: one observation for the prefill-side "
    "export gather, one for the decode-side install dispatch")
_M_TIER_DEPTH = registry().gauge(
    "sparkdl_disagg_tier_depth",
    "queued requests per disaggregated serving tier",
    labels=("tier",))
_M_PHASE_SECONDS = registry().histogram(
    "sparkdl_request_phase_seconds",
    "per-request latency attribution (ISSUE 17): where one request's "
    "wall time went — (queue,prefill) submit→take, (compute,prefill) "
    "take→export, (wire,handoff) export→decode-tier arrival, "
    "(queue,decode) arrival→admit, (compute,decode) admit→done. The "
    "five phases telescope: their sum IS the request's end-to-end "
    "latency (asserted by run-tests.sh)",
    labels=("phase", "tier"))


def observe_phase(phase: str, tier: str, seconds: float) -> None:
    """Record one request's time in one phase (clamped at 0 — phase
    boundaries are monotonic stamps, but cross-process re-anchoring can
    produce a negative hairline)."""
    _M_PHASE_SECONDS.observe(max(0.0, float(seconds)),
                             phase=phase, tier=tier)


class HandoffInstallError(RuntimeError):
    """The decode tier failed to install a transferred KV handoff (the
    ``handoff.install`` fault site). A REQUEST-level error by the
    fabric's taxonomy — the host is healthy — but a retryable one: the
    :class:`~sparkdl_tpu.disagg.PhaseRouter` answers it by re-queuing
    the victim at the PREFILL tier's queue head (zero accepted
    requests lost; the cross-tier half of the drain contract)."""


def _enc(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {"dtype": a.dtype.name, "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _dec(d: dict) -> np.ndarray:
    try:
        dt = np.dtype(d["dtype"])
    except TypeError:
        # bfloat16 etc. live in ml_dtypes (a jax dependency), not numpy
        import ml_dtypes

        dt = np.dtype(getattr(ml_dtypes, d["dtype"]))
    return np.frombuffer(
        base64.b64decode(d["data"]), dtype=dt).reshape(d["shape"])


@dataclasses.dataclass
class KVHandoff:
    """One finished prefill, packaged for the tier crossing (see module
    docstring). ``k``/``v`` are the prompt's blocks in RAW pool storage
    ``[num_layers, n_blocks, block_size, heads, head_dim]`` (int8/bf16/
    fp32 per ``kv_dtype``); ``k_scale``/``v_scale`` are the int8
    layout's per-column fp32 scales ``[num_layers, n_blocks,
    block_size]`` (None otherwise). ``first_token`` seeds decode — the
    argmax the final prefill chunk computed, so the decode tier never
    re-runs the prompt."""

    prompt: np.ndarray
    max_new_tokens: int
    first_token: int
    kv_dtype: str
    block_size: int
    k: np.ndarray
    v: np.ndarray
    k_scale: "np.ndarray | None" = None
    v_scale: "np.ndarray | None" = None
    request_id: int = 0
    deadline: "float | None" = None
    enqueued: float = 0.0
    trace_ctx: Any = None
    src_host: "str | None" = None
    #: monotonic stamp (LOCAL clock) of export completion on the
    #: prefill tier; re-anchored as elapsed age over the wire, exactly
    #: like the deadline — the ``handoff.wire`` span's start
    exported_at: "float | None" = None
    #: monotonic stamp (LOCAL clock) of arrival on the decode tier
    #: (``from_wire``/``submit_handoff``): the wire→decode-queue phase
    #: boundary
    arrived_at: "float | None" = None
    #: prefill-tier measured durations (clock-safe across processes):
    #: submit→take and take→export — the decode side publishes all five
    #: request phases from one place using these
    queue_wait_s: float = 0.0
    prefill_s: float = 0.0
    #: live flight-recorder incident id at export time (ISSUE 17): the
    #: decode tier adopts it so both tiers' postmortem bundles join
    incident_id: "str | None" = None

    @property
    def n_blocks(self) -> int:
        return int(self.k.shape[1])

    @property
    def wire_bytes(self) -> int:
        """K/V payload bytes this handoff moves (the quantity the int8
        wire-cost arithmetic in the module docstring bounds)."""
        n = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_scale is not None:
            n += int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        return n

    def to_wire(self) -> dict:
        """JSON-safe dict (base64 tensors) for the ``HostServer``
        transport. The absolute monotonic deadline ships as REMAINING
        seconds and re-anchors on arrival; ``exported_at`` ships the
        same way (as elapsed ``export_age_s``); ``trace_ctx`` crosses
        as a serialized :class:`~sparkdl_tpu.observability.tracing.
        SpanContext` so decode-tier spans parent into the prefill
        tier's trace (ISSUE 17)."""
        out = {
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "first_token": int(self.first_token),
            "kv_dtype": self.kv_dtype,
            "block_size": int(self.block_size),
            "k": _enc(self.k),
            "v": _enc(self.v),
            "request_id": int(self.request_id),
            "src_host": self.src_host,
            "queue_wait_s": float(self.queue_wait_s),
            "prefill_s": float(self.prefill_s),
        }
        trace = tracing.context_to_wire(self.trace_ctx)
        if trace is not None:
            out["trace"] = trace
        if self.incident_id:
            out["incident_id"] = str(self.incident_id)
        if self.deadline is not None:
            out["remaining_s"] = max(
                0.0, self.deadline - time.monotonic())
        if self.exported_at is not None:
            out["export_age_s"] = max(
                0.0, time.monotonic() - self.exported_at)
        if self.k_scale is not None:
            out["k_scale"] = _enc(self.k_scale)
            out["v_scale"] = _enc(self.v_scale)
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "KVHandoff":
        now = time.monotonic()
        deadline = None
        if "remaining_s" in d:
            deadline = now + float(d["remaining_s"])
        exported_at = None
        if "export_age_s" in d:
            exported_at = now - float(d["export_age_s"])
        return cls(
            prompt=np.asarray(d["prompt"], np.int32),
            max_new_tokens=int(d["max_new_tokens"]),
            first_token=int(d["first_token"]),
            kv_dtype=str(d["kv_dtype"]),
            block_size=int(d["block_size"]),
            k=_dec(d["k"]),
            v=_dec(d["v"]),
            k_scale=_dec(d["k_scale"]) if "k_scale" in d else None,
            v_scale=_dec(d["v_scale"]) if "v_scale" in d else None,
            request_id=int(d.get("request_id") or 0),
            deadline=deadline,
            enqueued=now,
            trace_ctx=tracing.context_from_wire(d.get("trace")),
            src_host=d.get("src_host"),
            exported_at=exported_at,
            arrived_at=now,
            queue_wait_s=float(d.get("queue_wait_s") or 0.0),
            prefill_s=float(d.get("prefill_s") or 0.0),
            incident_id=d.get("incident_id"),
        )
