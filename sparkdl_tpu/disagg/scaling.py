"""Per-tier autoscaling: each tier scales on ITS OWN pressure signal.

A disaggregated fabric decouples more than placement — it decouples
capacity planning. Prefill pressure is QUEUE DEPTH: prompts are bursty,
each occupies a slot briefly, and a backlog means the tier needs more
compute. Decode pressure is OCCUPANCY: requests camp on slots for their
whole generation span, and the binding resource is KV blocks — a
decode tier in a deferral streak is out of memory, not out of queue.

These readers adapt both signals to the
:class:`~sparkdl_tpu.autoscale.controller.AutoScaler`'s two-channel
``signals`` contract ``(queue_depth, burn)``: the prefill reader feeds
raw tier depth; the decode reader feeds waiting + running work per the
depth channel and maps KV exhaustion (a host's ``degraded`` health,
which an exhaustion streak sets) onto the burn channel — so the
existing control law (hysteresis, cooldown, veto) drives both tiers
unmodified, each against the bound that actually constrains it.

:func:`tier_autoscalers` wires the pair: each scaler binds its tier's
Router as the fabric actuator, so scale-down drains + parks a host
handle and scale-up re-opens a parked one (the ISSUE 16 rejoin path).
"""

from __future__ import annotations

import time
from typing import Callable

from sparkdl_tpu.disagg.handoff import _M_TIER_DEPTH

__all__ = [
    "decode_tier_signals",
    "prefill_tier_signals",
    "tier_autoscalers",
]


def prefill_tier_signals(phase_router) -> "Callable[[], tuple]":
    """An ``AutoScaler(signals=...)`` reader for the PREFILL tier:
    queued prompts across the tier's hosts (burn channel unused —
    prefill work has no per-token SLO of its own; the decode tier
    carries the latency objective)."""

    def read() -> "tuple[float, float]":
        depth = 0
        for handle in phase_router.prefill.host_handles():
            try:
                depth += int(handle.capacity().get("queue_depth") or 0)
            except Exception:
                continue
        _M_TIER_DEPTH.set(depth, tier="prefill")
        return float(depth), 0.0

    return read


def decode_tier_signals(phase_router) -> "Callable[[], tuple]":
    """An ``AutoScaler(signals=...)`` reader for the DECODE tier:
    occupied slots + queued handoffs on the depth channel; KV-block
    exhaustion — any host reading ``degraded``, which is exactly what
    a deferral streak sets — saturates the burn channel, so block
    starvation scales the tier up even while slots look free."""

    def read() -> "tuple[float, float]":
        pressure = 0
        depth = 0
        burn = 0.0
        for handle in phase_router.decode.host_handles():
            try:
                cap = handle.capacity()
                health = handle.health()
            except Exception:
                continue
            n = int(cap.get("n_slots") or 0)
            free = int(cap.get("free_slots") or 0)
            q = int(cap.get("queue_depth") or 0)
            pressure += max(0, n - free) + q
            depth += q
            if health.get("status") == "degraded":
                burn = 1.0
        _M_TIER_DEPTH.set(depth, tier="decode")
        return float(pressure), burn

    return read


def tier_autoscalers(phase_router, *, prefill_policy=None,
                     decode_policy=None, interval_s: float = 0.25,
                     clock=time.monotonic):
    """Build one :class:`AutoScaler` per tier (neither started — call
    ``.start()`` or drive ``tick()`` manually), each bound to its
    tier's Router and its tier's signal reader. Returns
    ``(prefill_scaler, decode_scaler)``."""
    from sparkdl_tpu.autoscale.controller import (
        AutoscalePolicy,
        AutoScaler,
    )

    prefill = AutoScaler(
        router=phase_router.prefill,
        policy=prefill_policy or AutoscalePolicy(),
        signals=prefill_tier_signals(phase_router),
        interval_s=interval_s, clock=clock)
    try:
        decode = AutoScaler(
            router=phase_router.decode,
            policy=decode_policy or AutoscalePolicy(),
            signals=decode_tier_signals(phase_router),
            interval_s=interval_s, clock=clock)
    except BaseException:
        prefill.close()
        raise
    return prefill, decode
