"""Image decode and numpy↔struct conversion.

Parity with the reference's imageIO module (SURVEY.md 2.8, [U:
python/sparkdl/image/imageIO.py]): ``imageArrayToStruct`` /
``imageStructToArray`` round-trip numpy arrays through the Spark image
struct, PIL decodes bytes, and ``readImagesWithCustomFn`` builds an image
DataFrame from files with a user decode function. Channel order follows the
Spark convention: structs hold BGR; arrays handed to/from models are RGB
unless stated otherwise.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Iterable, Sequence

import numpy as np
from PIL import Image

from sparkdl_tpu.image import schema
from sparkdl_tpu.image.schema import (
    OCV_TYPES,
    UNDEFINED_MODE,
    image_struct,
    ocv_type_for,
)


def imageArrayToStruct(arr: np.ndarray, origin: str = "") -> dict:
    """Convert an (H, W, C) or (H, W) numpy array to an image struct.

    The array is stored as-is (no channel flip): callers that hold RGB data
    and want Spark-convention BGR structs should pass ``rgb_to_bgr(arr)``
    or use :func:`imageArrayToStructBGR`.
    """
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.ndim != 3:
        raise ValueError(f"expected 2-D or 3-D image array, got shape {arr.shape}")
    if arr.dtype not in (np.uint8, np.float32):
        if np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(np.float32)
        elif np.issubdtype(arr.dtype, np.integer):
            arr = arr.astype(np.uint8)
        else:
            raise ValueError(f"unsupported image array dtype {arr.dtype}")
    height, width, channels = arr.shape
    ocv = ocv_type_for(arr.dtype, channels)
    data = np.ascontiguousarray(arr).tobytes()
    return image_struct(data, height, width, ocv.mode, channels, origin)


def imageArrayToStructBGR(arr_rgb: np.ndarray, origin: str = "") -> dict:
    """RGB array in, Spark-convention BGR struct out."""
    return imageArrayToStruct(rgb_to_bgr(arr_rgb), origin)


def imageStructToArray(img: dict) -> np.ndarray:
    """Convert an image struct back to an (H, W, C) numpy array (as stored,
    i.e. BGR for Spark-convention structs)."""
    mode = img["mode"]
    if mode == UNDEFINED_MODE:
        raise ValueError(f"cannot convert undefined image (origin={img.get('origin')!r})")
    if mode not in OCV_TYPES:
        raise ValueError(f"unsupported OpenCV mode {mode}")
    ocv = OCV_TYPES[mode]
    shape = (img["height"], img["width"], img["nChannels"])
    return np.frombuffer(img["data"], dtype=ocv.dtype).reshape(shape)


def rgb_to_bgr(arr: np.ndarray) -> np.ndarray:
    if arr.ndim == 3 and arr.shape[-1] >= 3:
        return arr[..., ::-1] if arr.shape[-1] == 3 else np.concatenate(
            [arr[..., 2::-1], arr[..., 3:]], axis=-1
        )
    return arr


bgr_to_rgb = rgb_to_bgr  # the flip is an involution


def PIL_decode_bytes(raw: bytes, origin: str = "") -> dict | None:
    """Decode encoded image bytes (jpeg/png/...) to a BGR image struct, or
    None (→ undefined image row) if PIL cannot decode them."""
    try:
        img = Image.open(io.BytesIO(raw))
        img = img.convert("RGB") if img.mode not in ("RGB", "L") else img
        arr = np.asarray(img)
    except Exception:
        return None
    return imageArrayToStructBGR(arr, origin) if arr.ndim == 3 else imageArrayToStruct(arr, origin)


def native_decode_bytes(raw: bytes, origin: str = "") -> dict | None:
    """Like :func:`PIL_decode_bytes` but via the native libjpeg/libpng
    decoder (``native.decode``) — threaded C decode instead of PIL, the
    host-ingest equivalent of the reference's in-JVM decode (SURVEY.md
    2.2). Falls back to PIL when the native library is unavailable, for
    formats the native path does not cover (e.g. GIF), and for grayscale
    sources (PIL keeps them 1-channel CV_8UC1; the native decoder always
    emits RGB — deferring keeps the struct schema independent of which
    decoder a host happens to have)."""
    from sparkdl_tpu.native import decode as _native_decode

    if _native_decode.available():
        info = _native_decode.image_info(raw)
        if info is not None and info[2] == 3:
            # Pass the probed dims: skips a second header parse.
            arr = _native_decode.decode_resize(raw, info[0], info[1])
            if arr is not None:
                return imageArrayToStructBGR(arr, origin)
    return PIL_decode_bytes(raw, origin)


def undefined_image(origin: str = "") -> dict:
    return image_struct(b"", -1, -1, -1, UNDEFINED_MODE, origin)


def readImages(
    path: str | Sequence[str],
    numPartition: int | None = None,
    dataframe_backend: str = "local",
):
    """Read images with the default decoder (BGR structs): native
    libjpeg/libpng when the library is available, PIL otherwise — same
    structs either way (:func:`native_decode_bytes` defers to PIL for
    anything the native path would represent differently).

    Parity with the reference's ``imageIO.readImages`` / Spark's
    ``ImageSchema.readImages``."""
    return readImagesWithCustomFn(
        path, native_decode_bytes, numPartition, dataframe_backend
    )


def readImagesWithCustomFn(
    path: str | Sequence[str],
    decode_f: Callable[[bytes], np.ndarray | dict | None] | None = None,
    numPartition: int | None = None,
    dataframe_backend: str = "local",
):
    """Read image files into an image DataFrame.

    Reference parity (SURVEY.md 2.8): applies ``decode_f(bytes)`` per file;
    files the decoder rejects (returns None / raises) become "undefined
    image" rows, matching the reference's drop-nothing behavior. ``path``
    may be a directory, a glob-free file path, or an explicit list of paths.
    """
    from sparkdl_tpu.dataframe import make_dataframe

    paths = _expand_paths(path)
    if decode_f is None:
        decode_f = PIL_decode_bytes
    rows = []
    for p in paths:
        with open(p, "rb") as f:
            raw = f.read()
        try:
            decoded = decode_f(raw)
        except Exception:
            decoded = None
        if decoded is None:
            img = undefined_image(origin=p)
        elif isinstance(decoded, np.ndarray):
            img = imageArrayToStruct(decoded, origin=p)
        else:
            img = dict(decoded)
            img.setdefault("origin", p)
            if not img["origin"]:
                img["origin"] = p
        rows.append({"filePath": p, "image": img})
    return make_dataframe(rows, backend=dataframe_backend, num_partitions=numPartition)


def _expand_paths(path: str | Sequence[str]) -> list[str]:
    if isinstance(path, (list, tuple)):
        return [str(p) for p in path]
    if os.path.isdir(path):
        out = []
        for root, _, files in os.walk(path):
            for name in sorted(files):
                out.append(os.path.join(root, name))
        return sorted(out)
    return [path]
