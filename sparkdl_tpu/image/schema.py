"""Spark-compatible image struct schema.

Parity with the reference's image schema (SURVEY.md 2.8, [U:
python/sparkdl/image/imageIO.py] and pyspark.ml.image.ImageSchema): an image
is a struct of (origin, height, width, nChannels, mode, data) where ``mode``
is the OpenCV type code and ``data`` is the raw row-major bytes in **BGR**
channel order for 3/4-channel uint8 images — that convention is what lets
reference pipelines swap in this framework unchanged.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pyarrow as pa

# OpenCV type codes: code = depth + ((channels - 1) << 3)
# depth: CV_8U = 0, CV_32F = 5
_CV_8U, _CV_32F = 0, 5


def _ocv(depth: int, channels: int) -> int:
    return depth + ((channels - 1) << 3)


@dataclasses.dataclass(frozen=True)
class OcvType:
    name: str
    mode: int
    nChannels: int
    dtype: str


#: Supported OpenCV pixel types, keyed by mode code.
OCV_TYPES = {
    t.mode: t
    for t in [
        OcvType("CV_8UC1", _ocv(_CV_8U, 1), 1, "uint8"),
        OcvType("CV_8UC3", _ocv(_CV_8U, 3), 3, "uint8"),
        OcvType("CV_8UC4", _ocv(_CV_8U, 4), 4, "uint8"),
        OcvType("CV_32FC1", _ocv(_CV_32F, 1), 1, "float32"),
        OcvType("CV_32FC3", _ocv(_CV_32F, 3), 3, "float32"),
        OcvType("CV_32FC4", _ocv(_CV_32F, 4), 4, "float32"),
    ]
}

OCV_BY_NAME = {t.name: t for t in OCV_TYPES.values()}

#: Sentinel for "decode failed" rows, mirroring ImageSchema.undefinedImageType.
UNDEFINED_MODE = -1

IMAGE_FIELD_NAMES = ("origin", "height", "width", "nChannels", "mode", "data")


def ocv_type_for(dtype: np.dtype, channels: int) -> OcvType:
    dtype = np.dtype(dtype)
    if dtype == np.uint8:
        depth = _CV_8U
    elif dtype == np.float32:
        depth = _CV_32F
    else:
        raise ValueError(
            f"unsupported image dtype {dtype}; expected uint8 or float32"
        )
    mode = _ocv(depth, channels)
    if mode not in OCV_TYPES:
        raise ValueError(f"unsupported channel count {channels} for {dtype}")
    return OCV_TYPES[mode]


def arrow_image_type() -> "pa.StructType":
    """Arrow struct type matching Spark's ImageSchema.columnSchema."""
    return pa.struct(
        [
            pa.field("origin", pa.string()),
            pa.field("height", pa.int32()),
            pa.field("width", pa.int32()),
            pa.field("nChannels", pa.int32()),
            pa.field("mode", pa.int32()),
            pa.field("data", pa.binary()),
        ]
    )


def image_struct(
    data: bytes,
    height: int,
    width: int,
    mode: int,
    nChannels: int,
    origin: str = "",
) -> dict:
    return {
        "origin": origin,
        "height": int(height),
        "width": int(width),
        "nChannels": int(nChannels),
        "mode": int(mode),
        "data": data,
    }


def is_image_struct(value) -> bool:
    if not isinstance(value, dict):
        return False
    return {"height", "width", "nChannels", "mode", "data"}.issubset(value.keys())
