"""Backend dispatch: run a per-partition row transform on any DataFrame.

Transformers in this framework are written once, as a partition function
``fn(iter[dict]) -> iter[dict]`` (mirroring how the reference pushes work to
executors per partition, SURVEY.md 3.1). This module runs that function over:

  * LocalDataFrame   — in-process, partition by partition
  * pandas.DataFrame — treated as a single partition
  * pyarrow.Table    — treated as a single partition
  * pyspark DataFrame — via ``mapInPandas`` so the model executes inside
    executors next to their TPU hosts (gated: pyspark optional)
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator

import numpy as np
import pandas as pd

from sparkdl_tpu.dataframe.local import LocalDataFrame


def _spark_df_type():
    try:
        from pyspark.sql import DataFrame as SparkDataFrame

        return SparkDataFrame
    except ImportError:
        return None


def is_spark_df(df) -> bool:
    t = _spark_df_type()
    return t is not None and isinstance(df, t)


def make_dataframe(rows, backend: str = "local", num_partitions: int | None = None):
    if backend != "local":
        raise ValueError(f"unknown dataframe backend {backend!r}")
    return LocalDataFrame.from_rows(rows, num_partitions)


def columns_of(df) -> list[str]:
    if isinstance(df, LocalDataFrame):
        return df.columns
    if isinstance(df, pd.DataFrame):
        return list(df.columns)
    try:
        import pyarrow as pa

        if isinstance(df, pa.Table):
            return df.column_names
    except ImportError:
        pass
    if is_spark_df(df):
        return df.columns
    raise TypeError(f"unsupported DataFrame type {type(df)}")


def transform_partitions(
    df,
    fn: Callable[[Iterator[dict]], Iterable[dict]],
    output_schema: "list[tuple[str, str]] | None" = None,
):
    """Apply ``fn`` per partition, returning a DataFrame of the same backend.

    ``output_schema`` is a list of (name, spark_ddl_type) for the *added*
    columns; it is required for the pyspark backend (mapInPandas needs a
    schema) and ignored for local backends.
    """
    if isinstance(df, LocalDataFrame):
        return df.mapPartitions(fn)
    if isinstance(df, pd.DataFrame):
        rows = list(fn(iter(df.to_dict("records"))))
        return pd.DataFrame(rows)
    try:
        import pyarrow as pa

        if isinstance(df, pa.Table):
            rows = list(fn(iter(df.to_pylist())))
            return pa.Table.from_pylist(rows)
    except ImportError:
        pass
    if is_spark_df(df):
        return _transform_spark(df, fn, output_schema)
    raise TypeError(f"unsupported DataFrame type {type(df)}")


def _transform_spark(df, fn, output_schema):
    """pyspark path: ship ``fn`` to executors via mapInPandas.

    Each executor partition becomes an iterator of pandas chunks; we flatten
    to row dicts, run the same partition function the local backends use,
    and re-assemble pandas frames. One JAX process per executor does the TPU
    work (SURVEY.md §7 design stance: Spark pumps data, JAX owns execution).
    """
    if output_schema is None:
        raise ValueError("output_schema is required for the pyspark backend")
    in_schema = df.schema
    from pyspark.sql.types import StructType, _parse_datatype_string

    out_schema = StructType(list(in_schema.fields))
    for name, ddl in output_schema:
        field_type = _parse_datatype_string(ddl)
        out_schema = out_schema.add(name, field_type)

    def run(chunks: Iterator[pd.DataFrame]) -> Iterator[pd.DataFrame]:
        def rows() -> Iterator[dict]:
            for chunk in chunks:
                yield from chunk.to_dict("records")

        out_rows = []
        for r in fn(rows()):
            out_rows.append(r)
            if len(out_rows) >= 1024:
                yield pd.DataFrame(out_rows)
                out_rows = []
        if out_rows:
            yield pd.DataFrame(out_rows)

    return df.mapInPandas(run, schema=out_schema)


def get_column_block(rows: list[dict], col: str) -> np.ndarray:
    """Stack one column of a row block into a numpy array."""
    return np.asarray([r[col] for r in rows])
