from sparkdl_tpu.dataframe.local import LocalDataFrame, Row
from sparkdl_tpu.dataframe.adapters import (
    columns_of,
    is_spark_df,
    make_dataframe,
    transform_partitions,
)

__all__ = [
    "LocalDataFrame",
    "Row",
    "columns_of",
    "is_spark_df",
    "make_dataframe",
    "transform_partitions",
]
