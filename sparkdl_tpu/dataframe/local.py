"""A minimal partitioned DataFrame for running the pipeline without Spark.

The reference is unusable without a SparkSession; this framework keeps the
same API shape but lets every Transformer/Estimator run against this local
backend (partitioned rows, lazy-free) so single-host TPU inference needs no
JVM at all. With pyspark installed, the same transformers run over real
DataFrames via mapInPandas (see sparkdl_tpu/dataframe/spark.py).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Sequence

import pandas as pd


class Row(dict):
    """Dict with attribute access, standing in for pyspark.sql.Row."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:  # pragma: no cover
            raise AttributeError(name) from e


class LocalDataFrame:
    """List-of-rows DataFrame with explicit partitions.

    Partitioning is real (transformers batch within, never across,
    partitions) so the ragged-tail/bucketing behavior matches what Spark
    executors would see.
    """

    def __init__(self, partitions: Sequence[Sequence[dict]]):
        self._partitions = [list(map(Row, p)) for p in partitions]

    # -- construction -----------------------------------------------------
    @staticmethod
    def from_rows(rows: Iterable[dict], num_partitions: int | None = None) -> "LocalDataFrame":
        rows = list(rows)
        n = max(1, num_partitions or 1)
        if n == 1:
            return LocalDataFrame([rows])
        size = (len(rows) + n - 1) // n if rows else 0
        parts = [rows[i * size : (i + 1) * size] for i in range(n)] if size else [[] for _ in range(n)]
        return LocalDataFrame(parts)

    @staticmethod
    def from_pandas(pdf: pd.DataFrame, num_partitions: int | None = None) -> "LocalDataFrame":
        return LocalDataFrame.from_rows(pdf.to_dict("records"), num_partitions)

    # -- pyspark-like surface --------------------------------------------
    @property
    def columns(self) -> list[str]:
        for p in self._partitions:
            if p:
                return list(p[0].keys())
        return []

    def count(self) -> int:
        return sum(len(p) for p in self._partitions)

    def collect(self) -> list[Row]:
        return [r for p in self._partitions for r in p]

    def take(self, n: int) -> list[Row]:
        return self.collect()[:n]

    def first(self) -> Row | None:
        rows = self.take(1)
        return rows[0] if rows else None

    def select(self, *cols: str) -> "LocalDataFrame":
        return LocalDataFrame(
            [[{c: r[c] for c in cols} for r in p] for p in self._partitions]
        )

    def drop(self, *cols: str) -> "LocalDataFrame":
        keep = [c for c in self.columns if c not in cols]
        return self.select(*keep)

    def withColumnRenamed(self, old: str, new: str) -> "LocalDataFrame":
        def rename(r: dict) -> dict:
            return {new if k == old else k: v for k, v in r.items()}

        return LocalDataFrame([[rename(r) for r in p] for p in self._partitions])

    def repartition(self, n: int) -> "LocalDataFrame":
        return LocalDataFrame.from_rows(self.collect(), n)

    def limit(self, n: int) -> "LocalDataFrame":
        return LocalDataFrame.from_rows(self.collect()[:n], len(self._partitions))

    def toPandas(self) -> pd.DataFrame:
        return pd.DataFrame(self.collect())

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    # -- execution hooks used by transformers ----------------------------
    def mapPartitions(
        self, fn: Callable[[Iterator[dict]], Iterable[dict]]
    ) -> "LocalDataFrame":
        return LocalDataFrame([list(fn(iter(p))) for p in self._partitions])

    def __repr__(self) -> str:
        return (
            f"LocalDataFrame[{', '.join(self.columns)}]"
            f"(rows={self.count()}, partitions={self.num_partitions})"
        )
