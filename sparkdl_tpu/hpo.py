"""Hyperopt-style distributed hyperparameter search.

Reference parity: Databricks pairs HorovodRunner with Hyperopt's
``fmin``/``SparkTrials`` for distributed HPO (SURVEY.md 2.13; BASELINE.md
configs[4] "BERT-base fine-tune + Hyperopt distributed HPO"). Hyperopt
itself is an optional dependency: when installed, :func:`fmin` delegates to
it; otherwise a built-in random-search engine with the same call shape
runs, so the API works in hermetic environments.

Trials execute through a pluggable ``trial_runner`` — sequential by
default, or fan trials out however you like (each trial's objective may
itself call :class:`~sparkdl_tpu.runner.TPURunner` for multi-host
training, which is exactly the reference's Hyperopt+HorovodRunner nesting).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Sequence

import numpy as np

logger = logging.getLogger(__name__)

try:  # optional, API-compatible fast path
    import hyperopt as _hyperopt
except Exception:  # pragma: no cover - not in the hermetic image
    _hyperopt = None


# --------------------------------------------------------------------------
# Search-space primitives (hyperopt.hp-compatible subset)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Dist:
    kind: str
    label: str
    args: tuple

    def sample(self, rng: np.random.Generator) -> Any:
        if self.kind == "uniform":
            lo, hi = self.args
            return float(rng.uniform(lo, hi))
        if self.kind == "loguniform":
            lo, hi = self.args  # log-space bounds, as in hyperopt
            return float(np.exp(rng.uniform(lo, hi)))
        if self.kind == "quniform":
            lo, hi, q = self.args
            return float(np.round(rng.uniform(lo, hi) / q) * q)
        if self.kind == "choice":
            (options,) = self.args
            return options[int(rng.integers(len(options)))]
        raise ValueError(f"unknown dist {self.kind}")


class hp:
    """Drop-in subset of ``hyperopt.hp``."""

    @staticmethod
    def uniform(label: str, low: float, high: float) -> _Dist:
        return _Dist("uniform", label, (low, high))

    @staticmethod
    def loguniform(label: str, low: float, high: float) -> _Dist:
        return _Dist("loguniform", label, (low, high))

    @staticmethod
    def quniform(label: str, low: float, high: float, q: float) -> _Dist:
        return _Dist("quniform", label, (low, high, q))

    @staticmethod
    def choice(label: str, options: Sequence[Any]) -> _Dist:
        return _Dist("choice", label, (tuple(options),))


def sample_space(space: dict, rng: np.random.Generator) -> dict:
    return {
        k: v.sample(rng) if isinstance(v, _Dist) else v
        for k, v in space.items()
    }


@dataclasses.dataclass
class Trials:
    """Result log (hyperopt.Trials-shaped: .trials, .best_trial)."""

    trials: list[dict] = dataclasses.field(default_factory=list)

    @property
    def best_trial(self) -> dict:
        ok = [t for t in self.trials if t["status"] == "ok"]
        if not ok:
            raise RuntimeError("no successful trials")
        return min(ok, key=lambda t: t["loss"])

    @property
    def losses(self) -> list[float | None]:
        return [t.get("loss") for t in self.trials]


def _eval_trial(objective, i, params) -> dict:
    """One trial -> result record; failures never kill the sweep."""
    try:
        out = objective(params)
        loss = out["loss"] if isinstance(out, dict) else float(out)
        extra = out if isinstance(out, dict) else {}
        return {"tid": i, "params": params, "loss": float(loss),
                "status": "ok", **{k: v for k, v in extra.items()
                                   if k not in ("loss", "status")}}
    except Exception as e:
        logger.warning("trial %d failed: %s", i, e)
        return {"tid": i, "params": params, "loss": None,
                "status": "fail", "error": repr(e)}


def _run_trials_processes(objective, candidates, parallelism,
                          pin_devices: "list[int] | None" = None
                          ) -> list[dict]:
    """Each trial in a FRESH interpreter (own jax runtime/devices), at
    most ``parallelism`` concurrent — the single-host analogue of
    SparkTrials' executor-side evaluation.

    On a TPU host, concurrent fresh interpreters contend for the libtpu
    lock, so each trial is PINNED to one local chip
    (``runner.backends.tpu_chip_pin_overrides``, round-robin over a free
    pool); trials beyond the chip count queue for a free chip rather
    than deadlocking. ``pin_devices`` overrides the autodetected chip
    list (``local_pinnable_chips``); CPU hosts detect no chips and run
    unpinned.
    """
    import subprocess
    import sys
    import tempfile
    import time as _time

    import cloudpickle

    from sparkdl_tpu.runner.backends import (
        local_pinnable_chips,
        tpu_chip_pin_overrides,
    )

    if pin_devices is None:
        pin_devices = local_pinnable_chips()
    if pin_devices and parallelism > len(pin_devices):
        logger.warning(
            "trial_runner='processes' parallelism=%d exceeds the %d local "
            "chip(s); excess trials queue for a free chip (pass a smaller "
            "parallelism to silence this)", parallelism, len(pin_devices),
        )
    free_chips = list(pin_devices)

    pending = list(enumerate(candidates))
    running: dict = {}  # popen -> (tid, params, result_path, chip)
    results: list[dict] = []

    with tempfile.TemporaryDirectory(prefix="sparkdl_hpo_") as workdir:
        def launch(i, params):
            payload = os.path.join(workdir, f"trial{i}.pkl")
            result = os.path.join(workdir, f"trial{i}.out")
            with open(payload, "wb") as f:
                cloudpickle.dump(
                    {"objective": objective, "params": params}, f)
            chip = None
            env = None
            if free_chips:
                chip = free_chips.pop(0)
                env = os.environ.copy()
                env.update(tpu_chip_pin_overrides(chip))
            p = subprocess.Popen(
                [sys.executable, "-m", "sparkdl_tpu._trial_worker",
                 payload, result],
                env=env,
            )
            running[p] = (i, params, result, chip)

        try:
            while pending or running:
                while (pending and len(running) < max(1, parallelism)
                       and (not pin_devices or free_chips)):
                    launch(*pending.pop(0))
                done = [p for p in running if p.poll() is not None]
                if not done:
                    _time.sleep(0.05)
                    continue
                for p in done:
                    i, params, rpath, chip = running.pop(p)
                    if chip is not None:
                        free_chips.append(chip)
                    try:
                        with open(rpath, "rb") as f:
                            r = cloudpickle.load(f)
                    except Exception as e:
                        r = {"loss": None, "status": "fail",
                             "error": f"worker died: exit "
                                      f"{p.returncode} ({e!r})"}
                    if r["status"] == "fail":
                        logger.warning("trial %d failed: %s", i,
                                       r.get("error"))
                    results.append({"tid": i, "params": params, **r})
        finally:
            # never orphan worker interpreters if the sweep loop raises
            for p in running:
                if p.poll() is None:
                    p.kill()
            for p in running:
                p.wait(timeout=10)
    results.sort(key=lambda r: r["tid"])
    return results


def _run_trials_spark(objective, candidates, parallelism,
                      spark=None) -> list[dict]:
    """SparkTrials equivalent: one Spark task per trial, fanned over the
    cluster's executors (the reference pairs Hyperopt's SparkTrials with
    HorovodRunner this way — SURVEY.md 2.13). ``spark`` may be a
    SparkSession or anything exposing ``sparkContext.parallelize``."""
    sc = None
    if spark is not None:
        sc = getattr(spark, "sparkContext", spark)
    else:
        try:
            from pyspark.sql import SparkSession

            active = SparkSession.getActiveSession()
            sc = active.sparkContext if active is not None else None
        except Exception:
            sc = None
    if sc is None:
        raise RuntimeError(
            "trial_runner='spark' needs a SparkSession (pass spark=..., "
            "or use 'processes' for single-host isolation)"
        )
    n_slices = max(1, min(parallelism, len(candidates)))
    rdd = sc.parallelize(list(enumerate(candidates)), n_slices)
    return sorted(
        rdd.map(lambda ip: _eval_trial(objective, ip[0], ip[1])).collect(),
        key=lambda r: r["tid"],
    )


def fmin(
    objective: Callable[[dict], float | dict],
    space: dict,
    *,
    max_evals: int = 20,
    seed: int = 0,
    parallelism: int = 1,
    trials: Trials | None = None,
    use_hyperopt: bool | None = None,
    trial_runner: "str | Callable" = "threads",
    spark=None,
) -> dict:
    """Minimise ``objective`` over ``space``; returns the best param dict.

    ``objective`` gets a concrete param dict and returns a float loss (or a
    dict with a ``loss`` key, hyperopt-style). With hyperopt installed and
    a serial configuration (default ``trial_runner`` "threads" and
    ``parallelism=1``) delegates to ``hyperopt.fmin`` + TPE — an explicit
    distributed request (``parallelism>1`` or a 'processes'/'spark'/
    callable ``trial_runner``) opts out, since TPE evaluates serially in
    the driver (pass ``use_hyperopt=True`` to force the TPE path anyway).
    Otherwise runs seeded random search with ``parallelism`` trials at a
    time through ``trial_runner``:

    - ``"threads"`` — driver threads (trials block on device work or a
      TPURunner job, so the GIL is not the limiter);
    - ``"processes"`` — one fresh interpreter per trial (own jax
      runtime), at most ``parallelism`` concurrent;
    - ``"spark"`` — one Spark task per trial over the cluster (the
      SparkTrials pairing of SURVEY.md 2.13; pass ``spark=`` or have an
      active session);
    - a callable ``f(objective, candidates, parallelism) -> results``.
    """
    if not callable(trial_runner) and trial_runner not in (
            "threads", "processes", "spark"):
        raise ValueError(
            f"unknown trial_runner {trial_runner!r}: expected 'threads', "
            "'processes', 'spark', or a callable"
        )
    if use_hyperopt is None:
        # hyperopt evaluates trials serially in the driver, so any explicit
        # signal of distributed intent — a non-default trial_runner OR
        # parallelism>1 — opts out of the auto-upgrade; only the default
        # serial configuration silently takes the TPE path.
        use_hyperopt = (
            _hyperopt is not None
            and trial_runner == "threads"
            and parallelism == 1
        )
        if _hyperopt is not None and not use_hyperopt:
            # the silent TPE -> seeded-random downgrade cost callers search
            # quality with no signal (ADVICE r5) — say which knob flipped
            # the gate and how to force TPE back on
            logger.warning(
                "hyperopt is installed but the distributed-intent gate "
                "(parallelism=%d, trial_runner=%r) selected seeded random "
                "search over TPE; pass use_hyperopt=True to force the "
                "serial TPE engine instead",
                parallelism, trial_runner,
            )
    if use_hyperopt:
        if _hyperopt is None:
            raise RuntimeError("hyperopt requested but not installed")
        if callable(trial_runner) or trial_runner != "threads":
            logger.warning(
                "hyperopt path evaluates trials serially in the driver; "
                "trial_runner=%r ignored — pass use_hyperopt=False for "
                "the distributed trial runners", trial_runner,
            )
        if parallelism > 1:
            logger.warning(
                "hyperopt path runs trials serially (TPE is sequential); "
                "parallelism=%d ignored — pass use_hyperopt=False for the "
                "parallel random-search engine", parallelism,
            )
        hp_space = {
            k: getattr(_hyperopt.hp, v.kind)(v.label, *(
                (list(v.args[0]),) if v.kind == "choice" else v.args
            )) if isinstance(v, _Dist) else v  # constants pass through
            for k, v in space.items()
        }
        ho_trials = _hyperopt.Trials()
        best = _hyperopt.fmin(
            objective, hp_space, algo=_hyperopt.tpe.suggest,
            max_evals=max_evals, rstate=np.random.default_rng(seed),
            trials=ho_trials,
        )
        # space_eval decodes hp.choice indices back to option values so the
        # return contract matches the built-in engine.
        best = dict(_hyperopt.space_eval(hp_space, best))
        if trials is not None:  # mirror the log into the caller's Trials
            for i, t in enumerate(ho_trials.trials):
                ok = t["result"].get("status") == _hyperopt.STATUS_OK
                # hyperopt stores encoded vals ({label: [v]}); decode each
                # trial through space_eval so params holds real option
                # values and trials.best_trial["params"] stays usable.
                vals = {
                    k: v[0]
                    for k, v in t["misc"]["vals"].items() if v
                }
                trials.trials.append({
                    "tid": i,
                    "params": dict(_hyperopt.space_eval(hp_space, vals)),
                    "loss": t["result"].get("loss") if ok else None,
                    "status": "ok" if ok else "fail",
                })
        return best

    trials = trials if trials is not None else Trials()
    rng = np.random.default_rng(seed)
    candidates = [sample_space(space, rng) for _ in range(max_evals)]

    if callable(trial_runner):
        results = trial_runner(objective, candidates, parallelism)
    elif trial_runner == "spark":
        results = _run_trials_spark(objective, candidates, parallelism,
                                    spark=spark)
    elif trial_runner == "processes":
        results = _run_trials_processes(objective, candidates, parallelism)
    else:  # "threads" (validated above)
        if parallelism <= 1:
            results = [_eval_trial(objective, i, p)
                       for i, p in enumerate(candidates)]
        else:
            with ThreadPoolExecutor(max_workers=parallelism) as pool:
                results = list(pool.map(
                    lambda ip: _eval_trial(objective, ip[0], ip[1]),
                    enumerate(candidates),
                ))
    trials.trials.extend(results)
    return dict(trials.best_trial["params"])
