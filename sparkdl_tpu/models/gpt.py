"""Decoder-only (GPT-style) language-model family, TPU-first.

The reference has no decoder models (its zoo is ImageNet CNNs; SURVEY.md
2.1) — this family exists because a complete TPU framework must cover the
dominant modern model shape. Design:

- **RoPE** rotary positions (no position table, length-extrapolating,
  TPU-friendly elementwise math that XLA fuses into the projections).
- **Causal attention** with the same impl dispatch as BERT: ``full``
  (masked softmax), ``flash`` (fused Pallas kernel, scores never hit HBM),
  ``ring`` (exact sequence-parallel attention over the ``sp`` axis for
  long context).
- **Tensor parallel by construction**: qkv/out and MLP kernels carry
  Megatron-style sharding metadata (``parallel.tensor_parallel``).
- **Optional MoE MLP** (``num_experts > 0``): every ``moe_every``-th block
  swaps its dense MLP for ``parallel.expert_parallel.MoEMlpBlock`` —
  dp x tp x ep compose in one model.
- **KV-cache generation**: an explicit functional cache (a pytree passed
  in and returned), so prefill + single-token decode jit cleanly and
  :func:`generate` is one ``lax.scan`` with no Python-level round trips.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sparkdl_tpu.parallel.expert_parallel import MoEMlpBlock
from sparkdl_tpu.parallel.ring_attention import ring_self_attention
from sparkdl_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)

_NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 1024
    #: "rope" (default) or "learned" (GPT-2-style position table — required
    #: for HF GPT-2 weight fidelity, see :func:`load_hf_gpt2`)
    positions: str = "rope"
    rope_base: float = 10000.0
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0
    #: "full" | "flash" (Pallas fused kernels) | "ring" (sp-sharded).
    #: "flash" covers the uncached forward (ops/flash_attention) AND
    #: cached prefill with a concrete idx (flash over the written prefix
    #: with a static causal q-offset — O(idx+L) keys, not O(max_len);
    #: 7.6x vs dense-over-buffer on chip). Only a traced-idx prefill
    #: (jitted streaming callers) falls back to the dense masked path.
    attn_impl: str = "full"
    #: opt-in ops/flash_decode kernel for the single-token cached step.
    #: Default OFF: chip-measured 0.24x of the dense path at serving
    #: shape (batch 64, L=4096, bench_attention.py round 5) — XLA's
    #: dense decode runs at the HBM roofline while the kernel's
    #: half-lane-tile D=64 blocks and per-(b,h) programs read the cache
    #: inefficiently. The kernel stays correct (oracle + ragged start
    #: masking) for shapes where streaming wins.
    flash_decode: bool = False
    sp_axis: str = "sp"
    #: collective schedule for ``attn_impl='ring'``: "ring" rotates K/V
    #: shards via ppermute with an online softmax (O(L/sp) resident
    #: keys, exact up to fp accumulation order); "allgather" gathers the
    #: K/V shards once and runs the dense masked softmax per query shard
    #: — BITWISE-identical to the single-device full path, the right
    #: choice at small sp where the gathered keys fit (serving uses it
    #: for the sp∈{1,2} prefill parity contract).
    sp_mode: str = "ring"
    #: 0 = dense MLPs; >0 = MoE with this many experts
    num_experts: int = 0
    moe_every: int = 2  #: every Nth block is MoE (when num_experts > 0)
    moe_k: int = 2
    moe_capacity_factor: float = 2.0
    dtype: Any = jnp.float32

    def __post_init__(self):
        # Loud at construction: a typo'd sp_mode would otherwise fall
        # through to the ring schedule and silently trade away the
        # allgather path's bitwise-parity guarantee.
        if self.sp_mode not in ("ring", "allgather"):
            raise ValueError(
                f"unknown sp_mode {self.sp_mode!r}: expected 'ring' or "
                "'allgather'"
            )

    @classmethod
    def tiny(cls, **kw) -> "GPTConfig":
        """Test-sized config (oracle/unit tests)."""
        defaults = dict(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_seq_len=64, dropout=0.0,
        )
        defaults.update(kw)
        return cls(**defaults)


def apply_rope(x: jax.Array, positions: jax.Array,
               base: float = 10000.0) -> jax.Array:
    """Rotary position embedding. x: [B, L, H, D]; positions: [B, L]."""
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[:, :, None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def init_cache(config: GPTConfig, batch: int, max_len: int,
               per_slot: bool = False) -> dict:
    """Zeroed KV cache for :func:`generate` / incremental decode.

    Layout: k/v stacked over layers, [num_layers, B, max_len, H, D];
    ``idx`` is the number of positions already written — a scalar for the
    lockstep :func:`generate` path, or (``per_slot=True``) a per-row [B]
    vector for continuous-batching serving where every batch row (slot)
    decodes at its own depth (``serving.continuous``). Per-slot steps
    write this call's L tokens at columns ``[idx[b], idx[b]+L)`` of each
    row — L=1 is the classic decode step, L=k is the speculative verify
    pass that scores a whole draft span in one dispatch. Prefill a
    joining row in its own scalar-idx cache and scatter it in.
    """
    hd = config.hidden_size // config.num_heads
    shape = (config.num_layers, batch, max_len, config.num_heads, hd)
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
        "idx": jnp.zeros((batch,) if per_slot else (), jnp.int32),
    }


def init_block_pool(config: GPTConfig, n_blocks: int,
                    block_size: int, dtype: str = "fp32") -> dict:
    """Zeroed block-paged KV pool for continuous serving
    (``serving.kv_blocks``): k/v stacked over layers,
    ``[num_layers, n_blocks, block_size, H, D]``.

    Unlike :func:`init_cache` (one dense row per batch slot, capacity
    ``batch x max_len`` whether or not tokens exist), the pool's
    capacity is ``n_blocks x block_size`` TOKENS shared by every slot: a
    slot maps its logical columns onto pool blocks through a block
    table, the serving engine gathers a virtual dense cache per decode
    step, and the same physical block can back the shared prompt prefix
    of many slots (``serving.prefix_cache``). Bookkeeping (free list,
    refcounts, tables) is host-side and lives in
    :class:`~sparkdl_tpu.serving.kv_blocks.KVBlockPool`.

    ``dtype`` picks the STORAGE layout (``serving.kv_blocks.KV_DTYPES``):

    - ``"fp32"`` — store at the model's compute dtype (``config.dtype``),
      the exact layout; gather/scatter are plain copies.
    - ``"bf16"`` — store bfloat16, dequantize to the compute dtype on
      gather: half the pool bytes per token.
    - ``"int8"`` — store int8 with one fp32 scale per written COLUMN
      (``k_scale``/``v_scale``, ``[num_layers, n_blocks, block_size]``,
      riding the block structure): ~4x fewer pool bytes per token. The
      quantize/dequantize math is :func:`quantize_kv` /
      :func:`dequantize_kv`, fused by the serving engine into its paged
      gather/scatter programs — compute always runs at ``config.dtype``;
      only the resident pool is compressed.
    """
    hd = config.hidden_size // config.num_heads
    shape = (config.num_layers, n_blocks, block_size,
             config.num_heads, hd)
    store = {"fp32": config.dtype, "bf16": jnp.bfloat16,
             "int8": jnp.int8}.get(dtype)
    if store is None:
        raise ValueError(
            f"unknown KV pool dtype {dtype!r} (fp32 | bf16 | int8)")
    pool = {
        "k": jnp.zeros(shape, store),
        "v": jnp.zeros(shape, store),
    }
    if dtype == "int8":
        pool["k_scale"] = jnp.zeros(shape[:3], jnp.float32)
        pool["v_scale"] = jnp.zeros(shape[:3], jnp.float32)
    return pool


def quantize_kv(x: jax.Array) -> "tuple[jax.Array, jax.Array]":
    """Symmetric per-column int8 quantization of K/V columns.

    ``x`` is ``[..., H, D]`` (any leading index shape); returns
    ``(int8 values, fp32 scales[...])`` with one scale per column — the
    absmax maps to ±127, so requantize(dequantize(q, s)) == (q, s)
    exactly (the property that makes copy-on-write prefix sharing
    lossless under int8: a gathered-then-reinstalled block is
    bit-identical to its donor). Zero columns get a tiny floor scale
    and quantize to zero.
    """
    amax = jnp.max(jnp.abs(x), axis=(-2, -1))
    scale = (jnp.maximum(amax, 1e-30) / 127.0).astype(jnp.float32)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None, None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def dequantize_kv(q: jax.Array, scale: jax.Array,
                  dtype: Any = jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv`: int8 ``[..., H, D]`` columns and
    their per-column scales back to ``dtype``."""
    return (q.astype(jnp.float32) * scale[..., None, None]).astype(dtype)


class GPTAttention(nn.Module):
    config: GPTConfig
    layer_idx: int

    @nn.compact
    def __call__(self, x, *, cache: Optional[dict], train: bool,
                 positions: Optional[jax.Array] = None,
                 attention_mask: Optional[jax.Array] = None,
                 return_kv: bool = False):
        c = self.config
        h, nh = c.hidden_size, c.num_heads
        hd = h // nh
        b, l = x.shape[0], x.shape[1]

        q = ColumnParallelDense(h, dtype=c.dtype, name="q_proj")(x)
        k = ColumnParallelDense(h, dtype=c.dtype, name="k_proj")(x)
        v = ColumnParallelDense(h, dtype=c.dtype, name="v_proj")(x)
        q, k, v = (t.reshape(b, l, nh, hd) for t in (q, k, v))

        idx = cache["idx"] if cache is not None else jnp.zeros((), jnp.int32)
        #: per-slot cache: idx is [B] — every row decodes at its own depth
        #: (continuous batching); scalar idx is the lockstep generate path
        per_slot = jnp.ndim(idx) == 1
        if c.positions == "rope":
            if positions is None:
                # [1|B, L] -> broadcast: scalar idx rows share positions,
                # per-slot rows each count from their own depth
                positions = jnp.reshape(idx, (-1, 1)) + jnp.arange(l)[None, :]
                positions = jnp.broadcast_to(positions, (b, l))
            q = apply_rope(q, positions, c.rope_base)
            k = apply_rope(k, positions, c.rope_base)

        if cache is not None:
            # Write this call's keys/values at [idx, idx+L), then attend
            # over the full buffer with a position mask — one code path for
            # prefill (L>1) and decode (L=1), both jittable (idx is traced).
            # Overflow past the buffer would silently clamp the write while
            # the mask keeps advancing — catch it whenever idx is concrete
            # (eager streaming drivers; generate() pre-validates its scan).
            max_len = cache["k"].shape[2]
            if (not per_slot and not isinstance(idx, jax.core.Tracer)
                    and int(idx) + l > max_len):
                raise ValueError(
                    f"KV cache overflow: idx {int(idx)} + {l} new tokens > "
                    f"cache max_len {max_len}"
                )
            if per_slot:
                # Per-row scatter at columns [idx[b], idx[b]+L) — a true
                # indexed scatter touching B x L columns, not a masked
                # rewrite of the whole buffer (L=1 is the classic decode
                # step; L=k is the speculative verify span, every row at
                # its own depth). mode="drop" keeps the contract for
                # rows whose columns lie past the buffer (idle/retired
                # slots the serving engine has not reassigned yet): the
                # write is dropped (never clamped onto column max_len-1)
                # and the row stays garbage-but-finite — admission
                # control owns capacity, not this kernel.
                rows = jnp.arange(b)[:, None]
                cols = idx[:, None] + jnp.arange(l)[None, :]
                ck = cache["k"][self.layer_idx].at[rows, cols].set(
                    k.astype(c.dtype), mode="drop")
                cv = cache["v"][self.layer_idx].at[rows, cols].set(
                    v.astype(c.dtype), mode="drop")
            else:
                ck = jax.lax.dynamic_update_slice(
                    cache["k"][self.layer_idx], k.astype(c.dtype),
                    (0, idx, 0, 0),
                )
                cv = jax.lax.dynamic_update_slice(
                    cache["v"][self.layer_idx], v.astype(c.dtype),
                    (0, idx, 0, 0),
                )
            new_entry = (ck, cv)
            if (c.attn_impl == "flash" and l == 1 and c.flash_decode
                    and not per_slot):
                # opt-in single-query flash decode (see GPTConfig:
                # dense wins at serving shapes; kernel kept for shapes
                # where streaming the cache beats the score round-trip)
                from sparkdl_tpu.ops.flash_decode import flash_decode

                start = None
                if attention_mask is not None:
                    # left-padded rows: first valid buffer column per row
                    start = jnp.argmax(
                        attention_mask.astype(jnp.int32), axis=1
                    )
                ctx = flash_decode(q, ck, cv, idx, start=start)
            elif (c.attn_impl == "flash" and l > 1 and not per_slot
                  and not isinstance(idx, jax.core.Tracer)):
                # cached PREFILL with concrete idx (generate()'s eager
                # prefill is always idx=0): flash over the WRITTEN prefix
                # only — O(idx+L) keys per query instead of the dense
                # path's O(max_len) over every unwritten buffer column.
                # Queries sit at global positions [idx, idx+L), hence the
                # static q_offset in the kernel's causal mask.
                from sparkdl_tpu.ops.flash_attention import flash_attention

                end = int(idx) + l
                kv_mask = (attention_mask[:, :end]
                           if attention_mask is not None else None)
                ctx = flash_attention(
                    q, ck[:, :end], cv[:, :end], kv_mask,
                    causal=True, q_offset=int(idx),
                )
            else:
                # prefill (L>1), non-flash decode, and every per-slot step:
                # dense masked path. q_pos is [1, L] (lockstep) or [B, 1]
                # (per-slot), so the causal mask is per-row exactly when
                # rows sit at different depths.
                max_len = ck.shape[1]
                q_pos = jnp.reshape(idx, (-1, 1)) + jnp.arange(l)  # [1|B, L]
                k_pos = jnp.arange(max_len)  # [max_len]
                mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]
                if attention_mask is not None:
                    # [B, max_len] buffer-column validity (pad columns of
                    # left-padded ragged prompts are False forever)
                    mask = mask & attention_mask[:, None, None, :]
                s = jnp.einsum(
                    "bqhd,bkhd->bhqk", q, ck,
                    preferred_element_type=jnp.float32,
                ) / math.sqrt(hd)
                s = jnp.where(mask, s, _NEG_INF)
                p = jax.nn.softmax(s, axis=-1).astype(c.dtype)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", p, cv)
        else:
            # return_kv: hand the (post-rope) K/V of this uncached
            # forward to the caller — the prefill half of sequence
            # parallelism (sp_prefill): each sp shard's K/V row feeds
            # the serving cache without a second projection pass.
            new_entry = (k.astype(c.dtype), v.astype(c.dtype)) \
                if return_kv else None
            if attention_mask is not None and c.attn_impl != "full":
                raise ValueError(
                    "attention_mask on the uncached forward requires "
                    f"attn_impl='full' (got {c.attn_impl!r}); the flash/"
                    "ring kernels take ragged batches only through the "
                    "KV-cached generate() path"
                )
            if c.attn_impl == "flash":
                from sparkdl_tpu.ops.flash_attention import flash_attention

                ctx = flash_attention(q, k, v, causal=True)
            elif c.attn_impl == "ring" and c.sp_mode == "allgather":
                from sparkdl_tpu.parallel.ring_attention import (
                    allgather_self_attention,
                )

                ctx = allgather_self_attention(
                    q, k, v, axis_name=c.sp_axis, causal=True
                )
            elif c.attn_impl == "ring":
                ctx = ring_self_attention(
                    q, k, v, axis_name=c.sp_axis, causal=True
                )
            else:
                s = jnp.einsum(
                    "bqhd,bkhd->bhqk", q, k,
                    preferred_element_type=jnp.float32,
                ) / math.sqrt(hd)
                causal = jnp.tril(jnp.ones((l, l), bool))[None, None]
                if attention_mask is not None:
                    causal = causal & attention_mask[:, None, None, :]
                s = jnp.where(causal, s, _NEG_INF)
                p = jax.nn.softmax(s, axis=-1).astype(c.dtype)
                p = nn.Dropout(c.dropout, deterministic=not train)(p)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)

        out = RowParallelDense(h, dtype=c.dtype, name="out_proj")(
            ctx.reshape(b, l, h)
        )
        return out, new_entry


class GPTBlock(nn.Module):
    config: GPTConfig
    layer_idx: int

    @nn.compact
    def __call__(self, x, *, cache: Optional[dict], train: bool,
                 positions: Optional[jax.Array] = None,
                 attention_mask: Optional[jax.Array] = None,
                 return_kv: bool = False):
        c = self.config
        a, new_entry = GPTAttention(c, self.layer_idx, name="attn")(
            nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="ln_1")(x),
            cache=cache, train=train, positions=positions,
            attention_mask=attention_mask, return_kv=return_kv,
        )
        x = x + nn.Dropout(c.dropout, deterministic=not train)(a)

        h = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="ln_2")(x)
        is_moe = c.num_experts > 0 and (self.layer_idx % c.moe_every
                                        == c.moe_every - 1)
        if is_moe:
            m = MoEMlpBlock(
                num_experts=c.num_experts,
                hidden_features=c.intermediate_size,
                k=c.moe_k, capacity_factor=c.moe_capacity_factor,
                dtype=c.dtype, name="moe_mlp",
            )(h)
        else:
            up = ColumnParallelDense(c.intermediate_size, dtype=c.dtype,
                                     name="up")(h)
            m = RowParallelDense(c.hidden_size, dtype=c.dtype, name="down")(
                nn.gelu(up)
            )
        x = x + nn.Dropout(c.dropout, deterministic=not train)(m)
        return x, new_entry


class GPTLMHeadModel(nn.Module):
    """Decoder LM. ``__call__(input_ids, cache=None)`` -> (logits, cache).

    Without a cache: full causal forward (training / scoring), attention
    impl per ``config.attn_impl``. With a cache from :func:`init_cache`:
    writes K/V at ``cache['idx']`` and returns the updated cache —
    the building block :func:`generate` scans. A PER-SLOT cache
    (``init_cache(..., per_slot=True)``, ``idx`` [B]) decodes every row at
    its own depth with a per-row causal mask and per-row K/V scatter —
    always the dense path; L=1 is the classic decode step and L=k scores
    a whole speculative draft span in one pass — which is what lets
    ``serving.continuous`` admit and retire rows mid-stream and verify
    k drafted tokens per dispatch.

    ``positions``: optional [B, L] global token positions for RoPE.
    REQUIRED under ``attn_impl='ring'`` (sequence sharded on ``sp``): each
    shard must pass its global positions, not 0..L/sp-1 — the ring kernel
    offsets its causal mask globally, and RoPE must agree with it.

    ``attention_mask``: optional key-validity mask excluding positions
    from every attention softmax (False = masked). Shape [B, L] (over
    this call's keys) on the uncached forward; [B, max_len] (over BUFFER
    columns) on the cached path, where pad columns of left-padded ragged
    prompts stay False for the whole generation. :func:`generate` builds
    both from its ``attention_mask`` argument.
    """

    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, *, cache: Optional[dict] = None,
                 train: bool = False,
                 positions: Optional[jax.Array] = None,
                 attention_mask: Optional[jax.Array] = None,
                 return_kv: bool = False):
        c = self.config
        wte = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                       name="wte")
        x = wte(input_ids)
        if c.positions == "learned":
            b, l = input_ids.shape
            idx = cache["idx"] if cache is not None else jnp.zeros((), jnp.int32)
            pos = positions
            if pos is None:
                pos = jnp.broadcast_to(
                    jnp.reshape(idx, (-1, 1)) + jnp.arange(l)[None, :], (b, l)
                )
            x = x + nn.Embed(c.max_seq_len, c.hidden_size, dtype=c.dtype,
                             name="wpe")(pos)
        x = nn.Dropout(c.dropout, deterministic=not train)(x)

        new_ks, new_vs = [], []
        for i in range(c.num_layers):
            x, entry = GPTBlock(c, i, name=f"h_{i}")(
                x, cache=cache, train=train, positions=positions,
                attention_mask=attention_mask, return_kv=return_kv,
            )
            if entry is not None:
                new_ks.append(entry[0])
                new_vs.append(entry[1])

        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="ln_f")(x)
        logits = wte.attend(x).astype(jnp.float32)  # weight-tied LM head

        if cache is not None:
            cache = {
                "k": jnp.stack(new_ks),
                "v": jnp.stack(new_vs),
                "idx": cache["idx"] + input_ids.shape[1],
            }
        elif return_kv:
            # uncached KV-returning forward (the sp prefill building
            # block): k/v stacked over layers for THIS call's tokens —
            # under shard_map, the caller's local shard; ``idx`` is the
            # local token count (a global prefill offsets it itself)
            cache = {
                "k": jnp.stack(new_ks),
                "v": jnp.stack(new_vs),
                "idx": jnp.asarray(input_ids.shape[1], jnp.int32),
            }
        return logits, cache


# ---------------------------------------------------------------------------
# HuggingFace GPT-2 weight conversion (torch state dict -> this pytree)
# ---------------------------------------------------------------------------

def config_from_hf_gpt2(hf_config) -> GPTConfig:
    """GPTConfig reproducing an HF ``GPT2Config`` (learned positions,
    tanh-gelu MLP — both already this module's conventions). Variants this
    forward cannot reproduce are rejected rather than silently diverging."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported GPT-2 activation {act!r}: this forward uses "
            "tanh-gelu (gelu_new)"
        )
    if not getattr(hf_config, "scale_attn_weights", True) or getattr(
        hf_config, "scale_attn_by_inverse_layer_idx", False
    ):
        raise ValueError(
            "unsupported GPT-2 attention scaling variant (requires "
            "scale_attn_weights=True, scale_attn_by_inverse_layer_idx=False)"
        )
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        positions="learned",
        layer_norm_eps=hf_config.layer_norm_epsilon,
        dropout=0.0,
    )


def load_hf_gpt2(hf_model) -> "tuple[GPTConfig, dict]":
    """Convert an HF ``GPT2Model``/``GPT2LMHeadModel`` (torch) into this
    module's (config, variables). GPT-2's Conv1D stores weights [in, out],
    the same layout as flax Dense kernels — no transposes; the fused
    c_attn splits into q/k/v. Oracle-tested: logits match the torch
    forward on the same tokens (tests/models/test_gpt.py)."""
    import numpy as np

    base = getattr(hf_model, "transformer", hf_model)  # LMHead or bare
    cfg = config_from_hf_gpt2(base.config)
    e = cfg.hidden_size

    def _np(t):
        return np.asarray(t.detach().cpu().numpy())

    def _ln(mod):
        return {"scale": _np(mod.weight), "bias": _np(mod.bias)}

    params: dict = {
        "wte": {"embedding": _np(base.wte.weight)},
        "wpe": {"embedding": _np(base.wpe.weight)},
        "ln_f": _ln(base.ln_f),
    }
    for i, blk in enumerate(base.h):
        w = _np(blk.attn.c_attn.weight)  # [E, 3E]
        bias = _np(blk.attn.c_attn.bias)  # [3E]
        qw, kw, vw = w[:, :e], w[:, e:2 * e], w[:, 2 * e:]
        qb, kb, vb = bias[:e], bias[e:2 * e], bias[2 * e:]
        params[f"h_{i}"] = {
            "ln_1": _ln(blk.ln_1),
            "ln_2": _ln(blk.ln_2),
            "attn": {
                "q_proj": {"kernel": qw, "bias": qb},
                "k_proj": {"kernel": kw, "bias": kb},
                "v_proj": {"kernel": vw, "bias": vb},
                "out_proj": {
                    "kernel": _np(blk.attn.c_proj.weight),
                    "bias": _np(blk.attn.c_proj.bias),
                },
            },
            "up": {
                "kernel": _np(blk.mlp.c_fc.weight),
                "bias": _np(blk.mlp.c_fc.bias),
            },
            "down": {
                "kernel": _np(blk.mlp.c_proj.weight),
                "bias": _np(blk.mlp.c_proj.bias),
            },
        }
    return cfg, {"params": params}


def sample_logits(
    logits: jax.Array, key: jax.Array, *,
    temperature: float, top_k: "int | None" = None,
    top_p: "float | None" = None,
) -> jax.Array:
    """One sampling step over [B, V] logits, jit-safe.

    temperature 0 = greedy (top_k/top_p ignored); otherwise temperature
    scaling, then optional top-k truncation, then optional top-p
    (nucleus) truncation — the standard serving controls, composable.
    """
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # HF-parity clamp: top_k beyond the vocab keeps everything
        # (serving defaults like 50 must not crash tiny-vocab models)
        top_k = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep every token whose preceding cumulative mass is < top_p
        # (the first token is always kept)
        keep = csum - probs < top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(logits < cutoff, _NEG_INF, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(
    model: GPTLMHeadModel,
    variables: Any,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive decode: prefill the prompt, then one lax.scan step
    per token (KV-cached, single jittable program — no Python loop).

    temperature 0 = greedy; >0 = sampled (requires ``rng``), with
    optional ``top_k`` / ``top_p`` (nucleus) truncation.
    Returns [B, prompt_len + max_new_tokens] token ids.

    Ragged batches: ``attention_mask`` ([B, prompt_len], 1 = real token)
    decodes unequal-length prompts together. Prompts must be LEFT-padded
    (the serving convention: every row's last prompt token sits in the
    final column, so one logits column feeds sampling for all rows). Pad
    columns are excluded from every attention softmax, and per-row RoPE/
    learned positions count real tokens only — under GREEDY decoding
    (temperature=0) row b of the output equals the unbatched ``generate``
    of row b's unpadded prompt (oracle: tests/models/test_gpt_ragged.py);
    sampled runs draw per-step noise shaped by the whole batch, so
    sampled rows match only in distribution. Output rows keep their left
    pads: ``[pads, prompt, generated]``.

    Multi-chip serving: sharding-transparent. Commit ``prompt_ids`` (and
    ``attention_mask``) to a dp mesh (``runtime.mesh.batch_sharding``)
    and the prefill, every scan-carried cache update, and sampling run
    SPMD over the local chips, token-identical to the unsharded run
    (tests/models/test_gpt_dp.py).
    """
    b, lp = prompt_ids.shape
    if max_len is None:
        max_len = lp + max_new_tokens
    elif max_len < lp + max_new_tokens:
        raise ValueError(
            f"max_len={max_len} < prompt_len {lp} + max_new_tokens "
            f"{max_new_tokens}: cache writes would silently clamp"
        )
    if (model.config.positions == "learned"
            and lp + max_new_tokens > model.config.max_seq_len):
        # RoPE extrapolates; a learned position table does not — lookups
        # past it would silently clamp to the last row.
        raise ValueError(
            f"prompt_len {lp} + max_new_tokens {max_new_tokens} exceeds the "
            f"learned position table (max_seq_len={model.config.max_seq_len})"
        )
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature>0) requires rng")
    if temperature <= 0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p only apply when sampling (temperature > 0)"
        )
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    positions = key_valid = pad_len = None
    if attention_mask is not None:
        if attention_mask.shape != (b, lp):
            raise ValueError(
                f"attention_mask shape {attention_mask.shape} != prompt "
                f"shape {(b, lp)}"
            )
        mask = jnp.asarray(attention_mask).astype(bool)
        # left-padded = rows non-decreasing (0...0 1...1), ≥1 real token.
        # Value checks need concrete data — inside a jitted caller the
        # mask is a tracer and the contract is the caller's to honor.
        if not isinstance(mask, jax.core.Tracer):
            if not bool(jnp.all(mask[:, 1:] >= mask[:, :-1])):
                raise ValueError(
                    "attention_mask must be left-padded (each row "
                    "0...01...1); right-padded prompts cannot share a "
                    "sampling column"
                )
            if not bool(jnp.all(mask[:, -1])):
                raise ValueError("every row needs at least one real token")
        pad_len = lp - mask.sum(axis=1)  # [B]
        # logical positions: pads clamp to 0 (masked out of attention)
        positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0)
        # buffer-column validity for the WHOLE generation: pad columns
        # stay False; every generated column is real
        key_valid = jnp.concatenate(
            [mask, jnp.ones((b, max_len - lp), bool)], axis=1
        )

    cache = init_cache(model.config, b, max_len)
    logits, cache = model.apply(variables, prompt_ids, cache=cache,
                                positions=positions,
                                attention_mask=key_valid)
    rng, key = jax.random.split(rng)
    tok = sample(logits[:, -1], key)

    def step(carry, _):
        cache, tok, rng = carry
        pos = (None if pad_len is None
               else (cache["idx"] - pad_len)[:, None])
        logits, cache = model.apply(variables, tok[:, None], cache=cache,
                                    positions=pos,
                                    attention_mask=key_valid)
        rng, key = jax.random.split(rng)
        nxt = sample(logits[:, -1], key)
        return (cache, nxt, rng), tok

    # step i consumes the token at position lp+i and emits it; after N
    # steps ``toks`` holds exactly the N generated tokens (the final
    # carry's token is the N+1th, beyond max_new_tokens — dropped).
    _, toks = jax.lax.scan(
        step, (cache, tok, rng), None, length=max_new_tokens
    )
    return jnp.concatenate([prompt_ids, toks.swapaxes(0, 1)], axis=1)


def sp_prefill(
    model: GPTLMHeadModel,
    variables: Any,
    prompt_ids: jax.Array,
    mesh: Any,
) -> "tuple[jax.Array, dict]":
    """Sequence-parallel prompt prefill: shard the TOKENS of one (long)
    prompt contiguously across the mesh's ``sp`` chips and run ONE
    forward in which every chip computes its token shard's Q/K/V and
    attention follows ``config.sp_mode``:

    - ``"ring"`` — K/V shards rotate around the ring via ``ppermute``
      (:func:`~sparkdl_tpu.parallel.ring_attention.ring_self_attention`),
      each hop folding the visiting block into an online softmax with
      causal masking per (query-shard, key-shard) offset pair. O(L/sp)
      resident keys per chip — the long-context schedule. Exact up to
      fp accumulation order.
    - ``"allgather"`` — gather the K/V shards once, dense masked
      softmax per query shard: **bitwise-identical** logits to the
      unsharded forward (the serving parity contract), right for small
      ``sp`` where the gathered keys fit.

    Requires ``config.attn_impl == "ring"``. Prompts whose length does
    not divide ``sp`` are right-padded internally (pad keys sit causally
    AFTER every real query, so they are invisible without a mask) and
    the pad positions sliced off the outputs. Returns
    ``(logits [B, L, V], cache)`` where ``cache`` is an
    :func:`init_cache`-shaped pytree holding the prompt's K/V (k/v
    ``[layers, B, L, H, D]``, ``idx = L``) — ready to seed decode.
    """
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.compat import shard_map

    c = model.config
    axis = c.sp_axis
    if c.attn_impl != "ring":
        raise ValueError(
            f"sp_prefill requires attn_impl='ring' (sp_mode="
            f"'ring'|'allgather'), got attn_impl={c.attn_impl!r}"
        )
    sp = int(mesh.shape[axis])
    b, l = prompt_ids.shape
    pad = (-l) % sp
    lpad = l + pad
    if c.positions == "learned" and lpad > c.max_seq_len:
        raise ValueError(
            f"prompt_len {l} (padded to {lpad} for sp={sp}) exceeds the "
            f"learned position table (max_seq_len={c.max_seq_len})"
        )
    ids = jnp.pad(jnp.asarray(prompt_ids, jnp.int32), ((0, 0), (0, pad)))
    # GLOBAL positions per shard — the ring kernel offsets its causal
    # mask globally and RoPE must agree with it (model docstring)
    positions = jnp.broadcast_to(jnp.arange(lpad)[None, :], (b, lpad))

    def local(variables, ids_l, pos_l):
        logits, kv = model.apply(
            variables, ids_l, positions=pos_l, return_kv=True)
        return logits, kv["k"], kv["v"]

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(None, axis), P(None, axis)),
        out_specs=(P(None, axis), P(None, None, axis),
                   P(None, None, axis)),
    )
    logits, ks, vs = fn(variables, ids, positions)
    cache = {"k": ks[:, :, :l], "v": vs[:, :, :l],
             "idx": jnp.asarray(l, jnp.int32)}
    return logits[:, :l], cache
