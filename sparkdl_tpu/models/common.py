"""Shared building blocks for the Flax named-model zoo.

The reference ships frozen TF GraphDefs per named model (SURVEY.md 2.1/2.2);
we ship hand-written Flax modules instead. Every weight-bearing layer is
named by construction order (``conv000``, ``bn000``, ``dense000``,
``sepdw000``/``seppw000``) via :class:`Namer`; the Keras→Flax weight
converter (models/keras_loader.py) replays the same ordering over a Keras
model's layers, so conversion is a mechanical per-type zip with no
name-table per architecture.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Namer:
    """Construction-order names for weight-bearing layers."""

    def __init__(self) -> None:
        self._counts: dict[str, int] = {}

    def next(self, kind: str) -> str:
        i = self._counts.get(kind, 0)
        self._counts[kind] = i + 1
        return f"{kind}{i:03d}"

    def conv(self) -> str:
        return self.next("conv")

    def bn(self) -> str:
        return self.next("bn")

    def dense(self) -> str:
        return self.next("dense")

    def sepdw(self) -> str:
        return self.next("sepdw")

    def seppw(self) -> str:
        return self.next("seppw")


class ZooModule(nn.Module):
    """Base for zoo models: dtype policy fields + layer helpers.

    ``dtype`` is the compute dtype (bfloat16 on TPU); params stay float32.
    """

    num_classes: int = 1000
    include_top: bool = True
    dtype: Any = jnp.float32

    def _conv(self, nm: Namer, x, features: int, kernel: int | tuple[int, int],
              strides: int = 1, padding: str = "SAME", use_bias: bool = True):
        if isinstance(kernel, int):
            kernel = (kernel, kernel)
        return nn.Conv(
            features,
            kernel,
            strides=(strides, strides),
            padding=padding,
            use_bias=use_bias,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=nm.conv(),
        )(x)

    def _bn(self, nm: Namer, x, train: bool, use_scale: bool = True,
            epsilon: float = 1e-3, momentum: float = 0.99):
        return nn.BatchNorm(
            use_running_average=not train,
            momentum=momentum,
            epsilon=epsilon,
            use_scale=use_scale,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=nm.bn(),
        )(x)

    def _dense(self, nm: Namer, x, features: int):
        return nn.Dense(
            features, dtype=self.dtype, param_dtype=jnp.float32, name=nm.dense()
        )(x)

    def _sepconv(self, nm: Namer, x, features: int, kernel: int = 3,
                 strides: int = 1, padding: str = "SAME", use_bias: bool = False):
        """SeparableConv2D = depthwise conv + pointwise 1x1 conv.

        Kept as two convs (XLA fuses the pointwise into the following op);
        names pair up with the single Keras SeparableConv2D layer.
        """
        in_ch = x.shape[-1]
        x = nn.Conv(
            in_ch,
            (kernel, kernel),
            strides=(strides, strides),
            padding=padding,
            feature_group_count=in_ch,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=nm.sepdw(),
        )(x)
        return nn.Conv(
            features,
            (1, 1),
            use_bias=use_bias,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=nm.seppw(),
        )(x)


def max_pool(x, window: int = 3, strides: int = 2, padding: str = "VALID"):
    # Stays on XLA's reduce_window/select_and_scatter: the gather-form
    # backward in ops/pooling.py oracle-matches but measured ~2x slower
    # in-program (PERF.md round 3 — the first-tap mask materializes an
    # s32 map and the tap accumulation doesn't fuse as tightly).
    return nn.max_pool(x, (window, window), (strides, strides), padding)


def avg_pool_keras(x, window: int = 3, strides: int = 1, padding: str = "SAME"):
    """Average pool matching Keras semantics: padded cells are excluded from
    the divisor (count_include_pad=False)."""
    return nn.avg_pool(
        x, (window, window), (strides, strides), padding, count_include_pad=False
    )


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


def zero_pad(x, pad: int):
    return jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
