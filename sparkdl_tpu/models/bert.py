"""BERT encoder family, TPU-first.

The reference's BERT story is only a benchmark config ("HorovodRunner
BERT-base fine-tune + Hyperopt HPO", BASELINE.md configs[4]); it has no
transformer code of its own — users bring a Keras model. Here the family is
first-class: a Flax encoder whose projection kernels carry Megatron-style
tensor-parallel sharding metadata (``parallel.tensor_parallel``) and whose
attention can run as exact ring attention over the ``sp`` mesh axis for
long sequences (``parallel.ring_attention``) — both capabilities the
reference never had, required by the TPU-native design brief.

Weight fidelity: :func:`load_hf_bert` converts a HuggingFace
``BertModel``/``BertForSequenceClassification`` state dict (torch, CPU)
into this module's pytree; the oracle test asserts the Flax forward matches
the torch forward on the same batch.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.parallel.ring_attention import ring_self_attention
from sparkdl_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)

_NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    #: "full" = plain softmax attention (padding-masked);
    #: "flash" = fused Pallas flash-attention kernel (ops.flash_attention)
    #: — the TPU hot path: scores never materialised in HBM;
    #: "ring" = sp-sharded exact ring attention (call under shard_map with
    #: the sequence dim split on ``sp_axis``).
    attn_impl: str = "full"
    sp_axis: str = "sp"
    dtype: Any = jnp.float32

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        """Test-sized config (oracle/unit tests)."""
        defaults = dict(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0,
        )
        defaults.update(kw)
        return cls(**defaults)


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, position_ids, *, train: bool):
        c = self.config
        we = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                      name="word_embeddings")(input_ids)
        pe = nn.Embed(c.max_position_embeddings, c.hidden_size, dtype=c.dtype,
                      name="position_embeddings")(position_ids)
        te = nn.Embed(c.type_vocab_size, c.hidden_size, dtype=c.dtype,
                      name="token_type_embeddings")(token_type_ids)
        x = we + pe + te
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="LayerNorm")(x)
        return nn.Dropout(c.hidden_dropout_prob, deterministic=not train)(x)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, *, train: bool):
        c = self.config
        h, nh = c.hidden_size, c.num_attention_heads
        hd = h // nh
        # QKV: column-parallel (heads split over tp); out: row-parallel.
        q = ColumnParallelDense(h, dtype=c.dtype, name="query")(x)
        k = ColumnParallelDense(h, dtype=c.dtype, name="key")(x)
        v = ColumnParallelDense(h, dtype=c.dtype, name="value")(x)
        b, l = x.shape[0], x.shape[1]
        q, k, v = (t.reshape(b, l, nh, hd) for t in (q, k, v))

        if c.attn_impl in ("ring", "flash"):
            if train and c.attention_probs_dropout_prob > 0:
                # Blockwise accumulation never materialises the probability
                # matrix, so attention-probs dropout cannot be applied on
                # the ring/flash paths (the usual flash-attention trade-off).
                import warnings

                warnings.warn(
                    f"attn_impl={c.attn_impl!r} skips attention-probs "
                    f"dropout (p={c.attention_probs_dropout_prob}); set "
                    "attention_probs_dropout_prob=0 to silence",
                    stacklevel=2,
                )
        if c.attn_impl == "flash":
            from sparkdl_tpu.ops.flash_attention import flash_attention

            ctx = flash_attention(
                q, k, v,
                kv_mask=None if attention_mask is None else attention_mask,
            )
        elif c.attn_impl == "ring":
            ctx = ring_self_attention(
                q, k, v,
                kv_mask=None if attention_mask is None else attention_mask,
                axis_name=c.sp_axis,
            )
        else:
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
            ) / np.sqrt(hd)
            if attention_mask is not None:
                s = jnp.where(
                    attention_mask[:, None, None, :], s, _NEG_INF
                )
            p = jax.nn.softmax(s, axis=-1).astype(c.dtype)
            p = nn.Dropout(
                c.attention_probs_dropout_prob, deterministic=not train
            )(p)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)

        ctx = ctx.reshape(b, l, h)
        return RowParallelDense(h, dtype=c.dtype, name="output_dense")(ctx)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, *, train: bool):
        c = self.config
        attn = BertSelfAttention(c, name="attention")(
            x, attention_mask, train=train
        )
        attn = nn.Dropout(c.hidden_dropout_prob, deterministic=not train)(attn)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="attention_LayerNorm")(x + attn)
        # Megatron MLP: column-parallel up, row-parallel down, one psum.
        h = ColumnParallelDense(
            c.intermediate_size, dtype=c.dtype, name="intermediate"
        )(x)
        h = nn.gelu(h, approximate=False)
        h = RowParallelDense(c.hidden_size, dtype=c.dtype, name="output")(h)
        h = nn.Dropout(c.hidden_dropout_prob, deterministic=not train)(h)
        return nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                            name="output_LayerNorm")(x + h)


class BertModel(nn.Module):
    """Encoder + tanh pooler over [CLS] (HF BertModel shape)."""

    config: BertConfig
    add_pooler: bool = True

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        *,
        train: bool = False,
    ):
        c = self.config
        b, l = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.broadcast_to(jnp.arange(l), (b, l))
        mask = None if attention_mask is None else attention_mask.astype(bool)

        x = BertEmbeddings(c, name="embeddings")(
            input_ids, token_type_ids, position_ids, train=train
        )
        for i in range(c.num_hidden_layers):
            x = BertLayer(c, name=f"layer_{i}")(x, mask, train=train)

        pooled = None
        if self.add_pooler:
            pooled = nn.tanh(
                nn.Dense(c.hidden_size, dtype=c.dtype, name="pooler")(x[:, 0])
            )
        return x, pooled


class BertForSequenceClassification(nn.Module):
    """Fine-tune head: pooled [CLS] -> dropout -> logits."""

    config: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 *, train: bool = False):
        _, pooled = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, train=train
        )
        pooled = nn.Dropout(
            self.config.hidden_dropout_prob, deterministic=not train
        )(pooled)
        return nn.Dense(self.num_labels, dtype=self.config.dtype,
                        name="classifier")(pooled)


# ---------------------------------------------------------------------------
# HuggingFace weight conversion (torch state dict -> this pytree)
# ---------------------------------------------------------------------------

def _t(w) -> np.ndarray:
    """torch tensor -> numpy, transposing Linear weights [out,in]->[in,out]."""
    a = np.asarray(w.detach().cpu().numpy())
    return a.T if a.ndim == 2 else a


def config_from_hf(hf_config) -> BertConfig:
    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_hidden_layers=hf_config.num_hidden_layers,
        num_attention_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        hidden_dropout_prob=hf_config.hidden_dropout_prob,
        attention_probs_dropout_prob=hf_config.attention_probs_dropout_prob,
    )


def load_hf_bert(hf_model) -> tuple[BertConfig, dict]:
    """Convert a HF ``BertModel`` (torch) into (config, flax variables).

    Accepts ``BertModel`` or anything with a ``.bert`` submodule
    (e.g. ``BertForSequenceClassification`` — its classifier head is
    converted too when present).
    """
    head = None
    bert = hf_model
    if hasattr(hf_model, "bert"):
        bert = hf_model.bert
        head = getattr(hf_model, "classifier", None)
    sd = {k: v for k, v in bert.state_dict().items()}
    cfg = config_from_hf(bert.config)

    p: dict[str, Any] = {}
    p["embeddings"] = {
        "word_embeddings": {"embedding": np.asarray(sd["embeddings.word_embeddings.weight"].cpu())},
        "position_embeddings": {"embedding": np.asarray(sd["embeddings.position_embeddings.weight"].cpu())},
        "token_type_embeddings": {"embedding": np.asarray(sd["embeddings.token_type_embeddings.weight"].cpu())},
        "LayerNorm": {
            "scale": np.asarray(sd["embeddings.LayerNorm.weight"].cpu()),
            "bias": np.asarray(sd["embeddings.LayerNorm.bias"].cpu()),
        },
    }
    for i in range(cfg.num_hidden_layers):
        hf = f"encoder.layer.{i}"
        p[f"layer_{i}"] = {
            "attention": {
                "query": {"kernel": _t(sd[f"{hf}.attention.self.query.weight"]),
                          "bias": np.asarray(sd[f"{hf}.attention.self.query.bias"].cpu())},
                "key": {"kernel": _t(sd[f"{hf}.attention.self.key.weight"]),
                        "bias": np.asarray(sd[f"{hf}.attention.self.key.bias"].cpu())},
                "value": {"kernel": _t(sd[f"{hf}.attention.self.value.weight"]),
                          "bias": np.asarray(sd[f"{hf}.attention.self.value.bias"].cpu())},
                "output_dense": {"kernel": _t(sd[f"{hf}.attention.output.dense.weight"]),
                                 "bias": np.asarray(sd[f"{hf}.attention.output.dense.bias"].cpu())},
            },
            "attention_LayerNorm": {
                "scale": np.asarray(sd[f"{hf}.attention.output.LayerNorm.weight"].cpu()),
                "bias": np.asarray(sd[f"{hf}.attention.output.LayerNorm.bias"].cpu()),
            },
            "intermediate": {"kernel": _t(sd[f"{hf}.intermediate.dense.weight"]),
                             "bias": np.asarray(sd[f"{hf}.intermediate.dense.bias"].cpu())},
            "output": {"kernel": _t(sd[f"{hf}.output.dense.weight"]),
                       "bias": np.asarray(sd[f"{hf}.output.dense.bias"].cpu())},
            "output_LayerNorm": {
                "scale": np.asarray(sd[f"{hf}.output.LayerNorm.weight"].cpu()),
                "bias": np.asarray(sd[f"{hf}.output.LayerNorm.bias"].cpu()),
            },
        }
    if "pooler.dense.weight" in sd:
        p["pooler"] = {"kernel": _t(sd["pooler.dense.weight"]),
                       "bias": np.asarray(sd["pooler.dense.bias"].cpu())}

    variables = {"params": p}
    if head is not None:
        variables = {"params": {
            "bert": p,
            "classifier": {"kernel": _t(head.weight),
                           "bias": np.asarray(head.bias.detach().cpu())},
        }}
    variables = jax.tree.map(jnp.asarray, variables)
    return cfg, variables
