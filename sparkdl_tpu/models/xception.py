"""Xception in Flax (keras.applications.xception-equivalent).

Named model of the reference (SURVEY.md 2.1). Separable convs are a
depthwise + pointwise conv pair (models/common._sepconv); all convs
bias-free, BN default epsilon. Residual 1x1 convs are constructed before
the block body, matching Keras construction order for weight conversion.

features = global-average-pooled block14 output (2048-d).
"""

from __future__ import annotations

import flax.linen as nn

from sparkdl_tpu.models.common import (
    Namer,
    ZooModule,
    global_avg_pool,
    max_pool,
)


class Xception(ZooModule):
    @nn.compact
    def __call__(self, x, train: bool = False):
        nm = Namer()

        def bn(x):
            return self._bn(nm, x, train)

        def sep(x, filters):
            return bn(self._sepconv(nm, x, filters, 3))

        # -- entry flow ----------------------------------------------------
        x = self._conv(nm, x, 32, 3, strides=2, padding="VALID", use_bias=False)
        x = nn.relu(bn(x))
        x = self._conv(nm, x, 64, 3, padding="VALID", use_bias=False)
        x = nn.relu(bn(x))

        # Residual conv/BN are created AFTER the block body (Keras
        # topological order, which the weight converter replays).
        # block2: no leading relu on the first sepconv
        y = sep(x, 128)
        y = sep(nn.relu(y), 128)
        res = bn(self._conv(nm, x, 128, 1, strides=2, use_bias=False))
        x = max_pool(y, 3, 2, "SAME") + res

        for filters in (256, 728):  # blocks 3-4
            y = sep(nn.relu(x), filters)
            y = sep(nn.relu(y), filters)
            res = bn(self._conv(nm, x, filters, 1, strides=2, use_bias=False))
            x = max_pool(y, 3, 2, "SAME") + res

        # -- middle flow: 8 identity blocks --------------------------------
        for _ in range(8):
            res = x
            for _ in range(3):
                x = sep(nn.relu(x), 728)
            x = x + res

        # -- exit flow -----------------------------------------------------
        y = sep(nn.relu(x), 728)
        y = sep(nn.relu(y), 1024)
        res = bn(self._conv(nm, x, 1024, 1, strides=2, use_bias=False))
        x = max_pool(y, 3, 2, "SAME") + res

        x = nn.relu(sep(x, 1536))
        x = nn.relu(sep(x, 2048))

        features = global_avg_pool(x)
        if not self.include_top:
            return features, None
        logits = self._dense(nm, features, self.num_classes)
        return features, nn.softmax(logits)
