"""Keras → Flax weight conversion.

Replaces the reference's "load Keras HDF5, freeze to GraphDef" path
(SURVEY.md 2.3/2.9): here Keras weights become a Flax variables pytree for
the hand-written zoo modules. Matching is by construction order per layer
type (see models/common.Namer): the k-th Keras Conv2D maps to ``conv{k:03d}``
and so on — no per-architecture name tables. Layout notes:

  Keras Conv2D kernel   (kh, kw, in, out)      == Flax Conv kernel
  Keras Dense kernel    (in, out)              == Flax Dense kernel
  Keras DepthwiseConv2D (kh, kw, in, mult)     -> transpose to (kh, kw, mult, in)
  Keras SeparableConv2D depthwise + pointwise  -> sepdwNNN + seppwNNN pair
  Keras BatchNormalization gamma/beta          -> params scale/bias
                           moving mean/var     -> batch_stats mean/var
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

from sparkdl_tpu.models.common import Namer


_AUTO_SUFFIX = re.compile(r"^(.*?)(?:_(\d+))?$")


def _auto_suffix_key(name: str) -> int:
    """Keras auto-names ('conv2d', 'conv2d_7') carry construction order in
    the suffix (global per-class counter, monotone within a model)."""
    m = _AUTO_SUFFIX.match(name)
    return int(m.group(2)) if m.group(2) else -1


def keras_to_flax_variables(kmodel, layer_order: str = "topo") -> dict[str, Any]:
    """Convert a Keras model's weights to a Flax variables dict
    ``{'params': ..., 'batch_stats': ...}`` under Namer's naming scheme.

    Because Namer counters are independent per layer type, only the
    *per-type* ordering matters. ``layer_order`` picks it:

      'topo'        — ``model.layers`` topological order (Keras's own
                      deterministic serialization order). Zoo modules whose
                      branches are written in this order (ResNet, VGG,
                      Xception) use it.
      'auto_suffix' — sort each type bucket by the auto-name numeric suffix,
                      recovering true construction order. Needed for
                      InceptionV3, whose parallel branches make topological
                      order differ from the source construction order the
                      Flax module mirrors.
    """
    import keras

    # bucket weight-bearing layers by kind, preserving topological order
    buckets: dict[str, list] = {"conv": [], "sep": [], "bn": [], "dense": []}
    for lyr in kmodel.layers:
        if isinstance(lyr, keras.layers.SeparableConv2D):
            buckets["sep"].append(lyr)
        elif isinstance(lyr, (keras.layers.Conv2D, keras.layers.DepthwiseConv2D)):
            buckets["conv"].append(lyr)
        elif isinstance(lyr, keras.layers.BatchNormalization):
            buckets["bn"].append(lyr)
        elif isinstance(lyr, keras.layers.Dense):
            buckets["dense"].append(lyr)
        elif lyr.get_weights():
            raise ValueError(
                f"unsupported weight-bearing layer {type(lyr).__name__} "
                f"({lyr.name}); zoo conversion handles conv/bn/dense families"
            )
    if layer_order == "auto_suffix":
        for b in buckets.values():
            b.sort(key=lambda l: _auto_suffix_key(l.name))
    elif layer_order != "topo":
        raise ValueError(f"unknown layer_order {layer_order!r}")

    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}
    nm = Namer()
    for lyr in buckets["conv"]:
        w = [np.asarray(a) for a in lyr.get_weights()]
        if isinstance(lyr, keras.layers.DepthwiseConv2D):
            p: dict[str, Any] = {"kernel": w[0].transpose(0, 1, 3, 2)}
        else:
            p = {"kernel": w[0]}
        if lyr.use_bias:
            p["bias"] = w[1]
        params[nm.conv()] = p
    for lyr in buckets["sep"]:
        w = [np.asarray(a) for a in lyr.get_weights()]
        params[nm.sepdw()] = {"kernel": w[0].transpose(0, 1, 3, 2)}
        p = {"kernel": w[1]}
        if lyr.use_bias:
            p["bias"] = w[2]
        params[nm.seppw()] = p
    for lyr in buckets["bn"]:
        w = [np.asarray(a) for a in lyr.get_weights()]
        i = 0
        bn: dict[str, Any] = {}
        if lyr.scale:
            bn["scale"] = w[i]
            i += 1
        if lyr.center:
            bn["bias"] = w[i]
            i += 1
        name = nm.bn()
        params[name] = bn
        stats[name] = {"mean": w[i], "var": w[i + 1]}
    for lyr in buckets["dense"]:
        w = [np.asarray(a) for a in lyr.get_weights()]
        p = {"kernel": w[0]}
        if lyr.use_bias:
            p["bias"] = w[1]
        params[nm.dense()] = p

    out: dict[str, Any] = {"params": params}
    if stats:
        out["batch_stats"] = stats
    return out


def prune_to_structure(converted: dict, initialized: dict) -> dict:
    """Drop converted entries the module does not define (e.g. the
    classifier head when loading top-ful weights into include_top=False).
    Missing entries still fail later in check_variables_match."""
    out: dict[str, Any] = {}
    for col, leaves in converted.items():
        if col not in initialized:
            continue
        out[col] = {k: v for k, v in leaves.items() if k in initialized[col]}
    return out


def check_variables_match(converted: dict, initialized: dict) -> None:
    """Raise with a readable diff if converted shapes/names disagree with a
    module's init shapes — the oracle tests' first line of defense."""
    import jax

    conv_flat = {
        "/".join(map(str, [getattr(k, "key", k) for k in path])): v.shape
        for path, v in jax.tree_util.tree_flatten_with_path(converted)[0]
    }
    init_flat = {
        "/".join(map(str, [getattr(k, "key", k) for k in path])): v.shape
        for path, v in jax.tree_util.tree_flatten_with_path(initialized)[0]
    }
    missing = sorted(set(init_flat) - set(conv_flat))
    extra = sorted(set(conv_flat) - set(init_flat))
    mismatched = sorted(
        k for k in set(conv_flat) & set(init_flat) if conv_flat[k] != init_flat[k]
    )
    if missing or extra or mismatched:
        lines = []
        for k in missing[:12]:
            lines.append(f"  missing from conversion: {k} {init_flat[k]}")
        for k in extra[:12]:
            lines.append(f"  extra in conversion:     {k} {conv_flat[k]}")
        for k in mismatched[:12]:
            lines.append(
                f"  shape mismatch: {k} converted {conv_flat[k]} vs init {init_flat[k]}"
            )
        raise ValueError("Keras->Flax conversion mismatch:\n" + "\n".join(lines))


def load_keras_model_file(path: str):
    """Load a Keras model from .h5 / .keras file (compile=False)."""
    import keras

    return keras.models.load_model(path, compile=False)
