"""Vision Transformer (ViT) family, TPU-first.

Beyond-parity addition: the reference's zoo is ImageNet CNNs (SURVEY.md
2.1); a complete modern framework needs the transformer vision family
too. Faithful to the HF ``ViTModel`` computation (google/vit-base-*):
conv patch embedding, prepended CLS token, learned position embeddings,
pre-LN encoder blocks with exact (non-tanh) GELU, final LayerNorm.

TPU-first choices, same design language as models/bert.py:

- qkv/out and MLP kernels carry Megatron-style tp sharding metadata
  (``parallel.tensor_parallel``);
- ``attn_impl='flash'`` routes the encoder attention through the fused
  Pallas kernel (no mask needed — ViT sequences are dense);
- zoo contract: ``module.apply(vars, x, train=False) -> (features,
  probs)`` so DeepImageFeaturizer/DeepImagePredictor drive it like any
  named CNN. ``features`` = final-LN CLS token (the HF featurization
  convention); ``probs`` from the classifier head when ``include_top``.

``load_hf_vit`` converts a transformers ``ViTModel``/
``ViTForImageClassification`` (torch) into this module's variables —
oracle-tested feature-level against the torch forward on a shared
random-init model (tests/models/test_vit.py), the same fidelity story as
``load_hf_gpt2``/``load_hf_bert``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12
    dropout: float = 0.0
    #: "full" | "flash" (fused Pallas kernel; dense attention, no mask)
    attn_impl: str = "full"
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def b16(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        """Test-sized config (oracle/unit tests)."""
        defaults = dict(
            image_size=32, patch_size=8, hidden_size=32, num_layers=2,
            num_heads=2, intermediate_size=64, dropout=0.0,
        )
        defaults.update(kw)
        return cls(**defaults)


class ViTSelfAttention(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        c = self.config
        h, nh = c.hidden_size, c.num_heads
        hd = h // nh
        q = ColumnParallelDense(h, dtype=c.dtype, name="query")(x)
        k = ColumnParallelDense(h, dtype=c.dtype, name="key")(x)
        v = ColumnParallelDense(h, dtype=c.dtype, name="value")(x)
        b, l = x.shape[0], x.shape[1]
        q, k, v = (t.reshape(b, l, nh, hd) for t in (q, k, v))

        if c.attn_impl == "flash":
            if train and c.dropout > 0:
                # blockwise accumulation never materialises the
                # probability matrix, so attention-probs dropout cannot
                # apply on the flash path (same caveat as models/bert.py)
                import warnings

                warnings.warn(
                    "attn_impl='flash' skips attention-probs dropout "
                    f"(p={c.dropout}); set dropout=0 to silence",
                    stacklevel=2,
                )
            from sparkdl_tpu.ops.flash_attention import flash_attention

            ctx = flash_attention(q, k, v)
        else:
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k,
                preferred_element_type=jnp.float32,
            ) / np.sqrt(hd)
            p = jax.nn.softmax(s, axis=-1).astype(c.dtype)
            p = nn.Dropout(c.dropout, deterministic=not train)(p)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return RowParallelDense(h, dtype=c.dtype, name="output_dense")(
            ctx.reshape(b, l, h)
        )


class ViTBlock(nn.Module):
    """Pre-LN transformer block (the ViT/HF ordering: LN -> attn ->
    +residual; LN -> MLP -> +residual)."""

    config: ViTConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        c = self.config
        a = ViTSelfAttention(c, name="attention")(
            nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="layernorm_before")(x),
            train=train,
        )
        x = x + nn.Dropout(c.dropout, deterministic=not train)(a)
        h = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="layernorm_after")(x)
        h = ColumnParallelDense(c.intermediate_size, dtype=c.dtype,
                                name="intermediate")(h)
        h = nn.gelu(h, approximate=False)
        h = RowParallelDense(c.hidden_size, dtype=c.dtype, name="output")(h)
        return x + nn.Dropout(c.dropout, deterministic=not train)(h)


class ViTModel(nn.Module):
    """Zoo-contract ViT: ``(features, probs)``; probs None without head.

    ``features`` is the final-LayerNorm CLS token ([B, hidden]).
    Construction fields mirror ZooModule so the registry builds it like
    any named model. ``num_classes`` defaults to the config's (which
    ``load_hf_vit`` sets from HF ``num_labels``, so converted classifier
    heads apply without re-specifying it).
    """

    config: ViTConfig = ViTConfig()
    num_classes: "int | None" = None  # None -> config.num_classes
    include_top: bool = True
    dtype: Any = None  # overrides config.dtype when set

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = self.config
        n_classes = (self.num_classes if self.num_classes is not None
                     else c.num_classes)
        if self.dtype is not None and self.dtype != c.dtype:
            c = dataclasses.replace(c, dtype=self.dtype)
        p = c.patch_size
        b = x.shape[0]
        if x.shape[1] != c.image_size or x.shape[2] != c.image_size:
            raise ValueError(
                f"ViT expects {c.image_size}x{c.image_size} inputs, got "
                f"{x.shape[1]}x{x.shape[2]}"
            )
        # patch embedding: conv PxP stride P == per-patch linear
        h = nn.Conv(c.hidden_size, (p, p), strides=(p, p),
                    padding="VALID", dtype=c.dtype,
                    param_dtype=jnp.float32, name="patch_embed")(
            jnp.asarray(x, c.dtype))
        h = h.reshape(b, -1, c.hidden_size)  # [B, N, H]

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, c.hidden_size),
            jnp.float32,
        )
        h = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(c.dtype), (b, 1, c.hidden_size)),
             h], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, c.num_patches + 1, c.hidden_size), jnp.float32,
        )
        h = h + pos.astype(c.dtype)
        h = nn.Dropout(c.dropout, deterministic=not train)(h)

        for i in range(c.num_layers):
            h = ViTBlock(c, name=f"layer_{i}")(h, train=train)

        h = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         name="layernorm")(h)
        features = h[:, 0].astype(jnp.float32)
        if not self.include_top:
            return features, None
        logits = nn.Dense(n_classes, dtype=c.dtype,
                          param_dtype=jnp.float32, name="classifier")(
            h[:, 0])
        return features, jax.nn.softmax(logits.astype(jnp.float32))


def vit_b16_builder(include_top: bool = True, dtype=jnp.float32,
                    num_classes: int = 1000) -> ViTModel:
    """Registry-shaped constructor for ViT-B/16 at 224px."""
    return ViTModel(
        config=ViTConfig.b16(dtype=dtype), num_classes=num_classes,
        include_top=include_top, dtype=dtype,
    )


# ---------------------------------------------------------------------------
# HuggingFace ViT weight conversion (torch state -> this pytree)
# ---------------------------------------------------------------------------

def config_from_hf_vit(hf_config) -> ViTConfig:
    if getattr(hf_config, "hidden_act", "gelu") not in ("gelu",):
        raise ValueError(
            f"unsupported ViT activation {hf_config.hidden_act!r}"
        )
    return ViTConfig(
        image_size=hf_config.image_size,
        patch_size=hf_config.patch_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        dropout=0.0,
        num_classes=getattr(hf_config, "num_labels", None) or 1000,
    )


def load_hf_vit(hf_model) -> "tuple[ViTConfig, dict]":
    """Convert a transformers ``ViTModel`` / ``ViTForImageClassification``
    into (config, variables). Torch Linear stores [out, in] — transposed
    into flax [in, out]; the patch conv transposes OIHW -> HWIO."""
    base = getattr(hf_model, "vit", hf_model)
    cfg = config_from_hf_vit(base.config)

    def _np(t):
        return np.asarray(t.detach().cpu().numpy())

    def _lin(mod):
        return {"kernel": _np(mod.weight).T, "bias": _np(mod.bias)}

    def _ln(mod):
        return {"scale": _np(mod.weight), "bias": _np(mod.bias)}

    emb = base.embeddings
    params: dict = {
        "patch_embed": {
            "kernel": _np(emb.patch_embeddings.projection.weight)
            .transpose(2, 3, 1, 0),
            "bias": _np(emb.patch_embeddings.projection.bias),
        },
        "cls_token": _np(emb.cls_token),
        "pos_embed": _np(emb.position_embeddings),
        "layernorm": _ln(base.layernorm),
    }
    for i, layer in enumerate(base.encoder.layer):
        att = layer.attention.attention
        params[f"layer_{i}"] = {
            "layernorm_before": _ln(layer.layernorm_before),
            "layernorm_after": _ln(layer.layernorm_after),
            "attention": {
                "query": _lin(att.query),
                "key": _lin(att.key),
                "value": _lin(att.value),
                "output_dense": _lin(layer.attention.output.dense),
            },
            "intermediate": _lin(layer.intermediate.dense),
            "output": _lin(layer.output.dense),
        }
    head = getattr(hf_model, "classifier", None)
    if head is not None and hasattr(head, "weight"):
        params["classifier"] = _lin(head)
    return cfg, {"params": params}
