from sparkdl_tpu.models.registry import (
    SUPPORTED_MODELS,
    ModelEntry,
    build_flax_model,
    build_keras_model,
    get_entry,
    registry,
)
from sparkdl_tpu.models.gpt import (
    GPTConfig,
    GPTLMHeadModel,
    config_from_hf_gpt2,
    generate,
    init_cache,
    load_hf_gpt2,
)
from sparkdl_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    config_from_hf,
    load_hf_bert,
)
from sparkdl_tpu.models.vit import (
    ViTConfig,
    ViTModel,
    config_from_hf_vit,
    load_hf_vit,
)

__all__ = [
    "SUPPORTED_MODELS",
    "ModelEntry",
    "build_flax_model",
    "build_keras_model",
    "get_entry",
    "registry",
    "GPTConfig",
    "GPTLMHeadModel",
    "config_from_hf_gpt2",
    "generate",
    "init_cache",
    "load_hf_gpt2",
    "BertConfig",
    "BertForSequenceClassification",
    "BertModel",
    "config_from_hf",
    "load_hf_bert",
    "ViTConfig",
    "ViTModel",
    "config_from_hf_vit",
    "load_hf_vit",
]
