from sparkdl_tpu.models.registry import (
    SUPPORTED_MODELS,
    ModelEntry,
    build_flax_model,
    build_keras_model,
    get_entry,
    registry,
)

__all__ = [
    "SUPPORTED_MODELS",
    "ModelEntry",
    "build_flax_model",
    "build_keras_model",
    "get_entry",
    "registry",
]
