from sparkdl_tpu.models.registry import (
    SUPPORTED_MODELS,
    ModelEntry,
    build_flax_model,
    build_keras_model,
    get_entry,
    registry,
)
from sparkdl_tpu.models.gpt import (
    GPTConfig,
    GPTLMHeadModel,
    generate,
    init_cache,
)
from sparkdl_tpu.models.bert import (
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    config_from_hf,
    load_hf_bert,
)

__all__ = [
    "SUPPORTED_MODELS",
    "ModelEntry",
    "build_flax_model",
    "build_keras_model",
    "get_entry",
    "registry",
    "GPTConfig",
    "GPTLMHeadModel",
    "generate",
    "init_cache",
    "BertConfig",
    "BertForSequenceClassification",
    "BertModel",
    "config_from_hf",
    "load_hf_bert",
]
