"""ResNet50 in Flax (keras.applications.resnet.ResNet50-equivalent).

One of the reference's named models (SURVEY.md 2.1). Architecture is the
original v1 bottleneck ResNet as Keras builds it: stride on the first 1x1
conv of each stage's first block, conv biases on, BN epsilon 1.001e-5.
Construction order mirrors Keras exactly so order-based weight conversion
(models/keras_loader.py) lines up: shortcut conv/BN created before the
block's main-path convs.
"""

from __future__ import annotations

import flax.linen as nn

from sparkdl_tpu.models.common import (
    Namer,
    ZooModule,
    global_avg_pool,
    max_pool,
    zero_pad,
)

_BN_EPS = 1.001e-5


class ResNet50(ZooModule):
    """Returns (features, logits); logits is None when include_top=False.

    features = global-average-pooled penultimate activations (2048-d), the
    featurization layer DeepImageFeaturizer exposes.
    """

    @nn.compact
    def __call__(self, x, train: bool = False):
        nm = Namer()

        def conv_bn_relu_chainless(x):  # stem
            x = zero_pad(x, 3)
            x = self._conv(nm, x, 64, 7, strides=2, padding="VALID")
            x = self._bn(nm, x, train, epsilon=_BN_EPS)
            x = nn.relu(x)
            x = zero_pad(x, 1)
            return max_pool(x, 3, 2, "VALID")

        def block(x, filters: int, stride: int = 1, conv_shortcut: bool = True):
            # Layer order replays Keras's serialized topology order:
            # 1_conv, 2_conv, 0_conv (shortcut), 3_conv — and BNs likewise.
            y = self._conv(nm, x, filters, 1, strides=stride)
            y = self._bn(nm, y, train, epsilon=_BN_EPS)
            y = nn.relu(y)
            y = self._conv(nm, y, filters, 3)
            y = self._bn(nm, y, train, epsilon=_BN_EPS)
            y = nn.relu(y)
            if conv_shortcut:
                sc = self._conv(nm, x, 4 * filters, 1, strides=stride)
                sc = self._bn(nm, sc, train, epsilon=_BN_EPS)
            else:
                sc = x
            y = self._conv(nm, y, 4 * filters, 1)
            y = self._bn(nm, y, train, epsilon=_BN_EPS)
            return nn.relu(y + sc)

        def stack(x, filters: int, blocks: int, stride: int):
            x = block(x, filters, stride=stride)
            for _ in range(blocks - 1):
                x = block(x, filters, conv_shortcut=False)
            return x

        x = conv_bn_relu_chainless(x)
        x = stack(x, 64, 3, stride=1)
        x = stack(x, 128, 4, stride=2)
        x = stack(x, 256, 6, stride=2)
        x = stack(x, 512, 3, stride=2)
        features = global_avg_pool(x)
        if not self.include_top:
            return features, None
        logits = self._dense(nm, features, self.num_classes)
        return features, nn.softmax(logits)
