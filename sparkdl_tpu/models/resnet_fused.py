"""ResNet50 training forward with fused Pallas BN epilogues.

Training-MFU work (PERF.md "Training MFU"; VERDICT r2 next #1): the plain
Flax model lets XLA lower each BN-train layer into separate stat-reduce
and normalize passes over HBM-resident activations. This module recomputes
the SAME network — same variable tree as :class:`models.resnet.ResNet50`,
so init/checkpoints/weight-conversion interchange — as a pure function
whose 1x1 convs run through :func:`ops.fused_gemm_bn.conv1x1_bn_stats`:

* every 1x1 conv emits its BN's batch moments from the GEMM accumulator
  (no stats pass over the conv output);
* the 3x3→1x1 seam fuses the 3x3's BN-normalize+ReLU into the 1x1's
  operand load (normalized activations never hit HBM).

The 7x7 stem, the 3x3 convs, and max-pool stay on XLA's lowerings,
which is where they are already strong (the gather-form pooling
backward in ops/pooling.py measured slower — see its docstring). Numerics: batch moments come from the f32
GEMM accumulator rather than a bf16 re-read — equal in f32, and within
bf16 rounding otherwise (the oracle test pins both).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from sparkdl_tpu.models.common import max_pool
from sparkdl_tpu.ops.fused_gemm_bn import conv1x1_bn_stats

_BN_EPS = 1.001e-5
_MOMENTUM = 0.99

import os as _os

#: Pallas-kernel gate: fused 1x1s with Cin below this go through XLA
#: (lane-starved shapes measured 4.7x slower — PERF.md round 3). Read
#: ONCE at import: the forward is jit-traced, so a later env change
#: could never take effect anyway.
_FUSED_MIN_CIN = int(_os.environ.get("SPARKDL_FUSED_MIN_CIN", "128"))

#: (filters, blocks, stride) per stage — resnet.py's stack calls
_STAGES = ((64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2))


def _bn_apply(p, stats, x, eps=_BN_EPS):
    """Normalize in x's dtype (the flax convention: f32 is for STATS
    only) — an f32 normalize materializes f32 copies of every activation,
    doubling the step's HBM traffic (measured on chip)."""
    scale = p["scale"] * lax.rsqrt(stats["var"] + eps)
    shift = p["bias"] - stats["mean"] * scale
    return x * scale.astype(x.dtype) + shift.astype(x.dtype)


def _moments(y):
    m = jnp.mean(y.astype(jnp.float32), axis=(0, 1, 2))
    v = jnp.maximum(
        jnp.mean(jnp.square(y.astype(jnp.float32)), axis=(0, 1, 2))
        - m * m, 0.0)
    return m, v


def resnet50_fused_apply(
    variables: "dict[str, Any]", x, *, train: bool = True,
    num_classes: int = 1000, include_top: bool = True,
    dtype=jnp.bfloat16,
):
    """Forward pass over a ``ResNet50`` variable tree with fused kernels.

    Returns ``((features, probs), new_batch_stats)`` when ``train`` else
    ``(features, probs)`` — matching ``model.apply(..., train=True,
    mutable=["batch_stats"])`` up to kernel numerics. ``probs`` is None
    when ``include_top`` is False.
    """
    params = variables["params"]
    batch_stats = variables["batch_stats"]
    new_stats: dict[str, dict] = {}
    ci = [0]  # conv counter
    bi = [0]  # bn counter

    x = jnp.asarray(x, dtype)

    def conv_name():
        n = f"conv{ci[0]:03d}"
        ci[0] += 1
        return n

    def bn_name():
        n = f"bn{bi[0]:03d}"
        bi[0] += 1
        return n

    def record(name, mean, var):
        old = batch_stats[name]
        new_stats[name] = {
            "mean": _MOMENTUM * old["mean"] + (1 - _MOMENTUM) * mean,
            "var": _MOMENTUM * old["var"] + (1 - _MOMENTUM) * var,
        }

    def bn_train(name, y):
        """XLA-path BN: batch moments + normalize (stem / 3x3 outputs
        whose normalize can't ride a following fused GEMM)."""
        p = params[name]
        if train:
            mean, var = _moments(y)
            record(name, mean, var)
        else:
            mean, var = batch_stats[name]["mean"], batch_stats[name]["var"]
        return _bn_apply(p, {"mean": mean, "var": var}, y)

    def conv_xla(name, y, stride=1, padding="SAME"):
        p = params[name]
        return lax.conv_general_dilated(
            y.astype(dtype), p["kernel"].astype(dtype),
            window_strides=(stride, stride), padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p["bias"].astype(dtype)

    def conv1x1_fused(name, bn, y, prev_bn=None, relu_in=False, stride=1):
        """1x1 conv; returns (raw_out, this-BN's batch moments).

        Routes through the fused Pallas GEMM only where it measured at or
        ahead of XLA's conv on chip (PERF.md round-3 microbench): stride 1
        and Cin >= 128 lanes. Small-Cin blocks are lane-starved on the
        MXU (K=64 leaves half the contraction idle — 4.7x slower), and
        stride-2 goes through XLA's conv to avoid a strided pre-copy; both
        fall back to the XLA GEMM/conv with a separate stats reduction.
        """
        p = params[name]
        cin = y.shape[-1]
        use_kernel = train and stride == 1 and cin >= _FUSED_MIN_CIN
        if use_kernel:
            out, mean, var = conv1x1_bn_stats(
                y, p["kernel"].astype(dtype), p["bias"],
                prev_bn=prev_bn, relu_in=relu_in, stride=stride,
            )
            record(bn, mean, var)
        elif train:
            if prev_bn is not None:
                mean_p, var_p, gamma, beta, eps = prev_bn
                y = _bn_apply(
                    {"scale": gamma, "bias": beta},
                    {"mean": mean_p, "var": var_p}, y, eps)
            if relu_in:
                y = jnp.maximum(y, 0.0)
            out = lax.conv_general_dilated(
                y.astype(dtype), p["kernel"].astype(dtype),
                window_strides=(stride, stride), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + p["bias"].astype(dtype)
            mean, var = _moments(out)
            record(bn, mean, var)
        else:
            if prev_bn is not None:
                mean_p, var_p, gamma, beta, eps = prev_bn
                y = _bn_apply(
                    {"scale": gamma, "bias": beta},
                    {"mean": mean_p, "var": var_p}, y, eps)
            if relu_in:
                y = jnp.maximum(y, 0.0)
            if stride != 1:
                y = y[:, ::stride, ::stride, :]
            out = lax.dot_general(
                y.astype(dtype), p["kernel"][0, 0].astype(dtype),
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) + p["bias"]
            mean, var = (batch_stats[bn]["mean"], batch_stats[bn]["var"])
        return out, (mean, var)

    # ---- stem -----------------------------------------------------------
    y = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
    y = conv_xla(conv_name(), y, stride=2, padding="VALID")
    y = jnp.maximum(bn_train(bn_name(), y), 0.0).astype(dtype)
    y = jnp.pad(y, ((0, 0), (1, 1), (1, 1), (0, 0)))
    y = max_pool(y, 3, 2)

    # ---- stages ---------------------------------------------------------
    def block(y, filters, stride, conv_shortcut):
        # conv/bn declaration order replays resnet.py: 1_conv, 2_conv,
        # [0_conv shortcut,] 3_conv
        c_a, b_a = conv_name(), bn_name()
        c_3, b_3 = conv_name(), bn_name()
        if conv_shortcut:
            c_s, b_s = conv_name(), bn_name()
        c_b, b_b = conv_name(), bn_name()

        a_raw, (m_a, v_a) = conv1x1_fused(c_a, b_a, y, stride=stride)
        pa = params[b_a]
        z1 = jnp.maximum(
            _bn_apply(pa, {"mean": m_a, "var": v_a}, a_raw), 0.0
        ).astype(dtype)
        y2 = conv_xla(c_3, z1)
        p3 = params[b_3]
        if train:
            m2, v2 = _moments(y2)
            record(b_3, m2, v2)
        else:
            m2, v2 = batch_stats[b_3]["mean"], batch_stats[b_3]["var"]
        # 3x3's BN-normalize+ReLU fused into the closing 1x1's load
        b_raw, (m_b, v_b) = conv1x1_fused(
            c_b, b_b, y2.astype(dtype),
            prev_bn=(m2, v2, p3["scale"], p3["bias"], _BN_EPS),
            relu_in=True,
        )
        if conv_shortcut:
            s_raw, (m_s, v_s) = conv1x1_fused(c_s, b_s, y, stride=stride)
            sc = _bn_apply(params[b_s], {"mean": m_s, "var": v_s}, s_raw)
        else:
            sc = y
        out = jnp.maximum(
            _bn_apply(params[b_b], {"mean": m_b, "var": v_b}, b_raw) + sc,
            0.0,
        )
        return out.astype(dtype)

    for filters, blocks, stride in _STAGES:
        y = block(y, filters, stride, conv_shortcut=True)
        for _ in range(blocks - 1):
            y = block(y, filters, 1, conv_shortcut=False)

    features = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    if not include_top:
        out = (features, None)
    else:
        p = params["dense000"]
        logits = features @ p["kernel"] + p["bias"]
        out = (features, jax.nn.softmax(logits))
    if train:
        return out, new_stats
    return out
