"""InceptionV3 in Flax (keras.applications.inception_v3-equivalent).

The reference's flagship featurizer model — its north-star benchmark is
InceptionV3 featurization throughput (BASELINE.md). Every conv is
bias-free and every BN is gamma-free (scale=False), per the Keras original.
Branch construction order inside each mixed block follows Keras so that
order-based weight conversion lines up; concatenation order ==
construction order.

features = global-average-pooled mixed10 output (2048-d).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.common import (
    Namer,
    ZooModule,
    avg_pool_keras,
    global_avg_pool,
    max_pool,
)


class InceptionV3(ZooModule):
    @nn.compact
    def __call__(self, x, train: bool = False):
        nm = Namer()

        def cb(x, filters, kh, kw, strides=1, padding="SAME"):
            x = self._conv(
                nm, x, filters, (kh, kw), strides=strides, padding=padding,
                use_bias=False,
            )
            x = self._bn(nm, x, train, use_scale=False)
            return nn.relu(x)

        def concat(*branches):
            return jnp.concatenate(branches, axis=-1)

        # -- stem ----------------------------------------------------------
        x = cb(x, 32, 3, 3, strides=2, padding="VALID")
        x = cb(x, 32, 3, 3, padding="VALID")
        x = cb(x, 64, 3, 3)
        x = max_pool(x, 3, 2, "VALID")
        x = cb(x, 80, 1, 1, padding="VALID")
        x = cb(x, 192, 3, 3, padding="VALID")
        x = max_pool(x, 3, 2, "VALID")

        # -- 3x inception-A (35x35), mixed0..2 -----------------------------
        for pool_filters in (32, 64, 64):
            b1 = cb(x, 64, 1, 1)
            b5 = cb(x, 48, 1, 1)
            b5 = cb(b5, 64, 5, 5)
            b3 = cb(x, 64, 1, 1)
            b3 = cb(b3, 96, 3, 3)
            b3 = cb(b3, 96, 3, 3)
            bp = avg_pool_keras(x, 3, 1, "SAME")
            bp = cb(bp, pool_filters, 1, 1)
            x = concat(b1, b5, b3, bp)

        # -- reduction-A, mixed3 -------------------------------------------
        b3 = cb(x, 384, 3, 3, strides=2, padding="VALID")
        bd = cb(x, 64, 1, 1)
        bd = cb(bd, 96, 3, 3)
        bd = cb(bd, 96, 3, 3, strides=2, padding="VALID")
        bp = max_pool(x, 3, 2, "VALID")
        x = concat(b3, bd, bp)

        # -- 4x inception-B (17x17), mixed4..7 -----------------------------
        for mid in (128, 160, 160, 192):
            b1 = cb(x, 192, 1, 1)
            b7 = cb(x, mid, 1, 1)
            b7 = cb(b7, mid, 1, 7)
            b7 = cb(b7, 192, 7, 1)
            bd = cb(x, mid, 1, 1)
            bd = cb(bd, mid, 7, 1)
            bd = cb(bd, mid, 1, 7)
            bd = cb(bd, mid, 7, 1)
            bd = cb(bd, 192, 1, 7)
            bp = avg_pool_keras(x, 3, 1, "SAME")
            bp = cb(bp, 192, 1, 1)
            x = concat(b1, b7, bd, bp)

        # -- reduction-B, mixed8 -------------------------------------------
        b3 = cb(x, 192, 1, 1)
        b3 = cb(b3, 320, 3, 3, strides=2, padding="VALID")
        b7 = cb(x, 192, 1, 1)
        b7 = cb(b7, 192, 1, 7)
        b7 = cb(b7, 192, 7, 1)
        b7 = cb(b7, 192, 3, 3, strides=2, padding="VALID")
        bp = max_pool(x, 3, 2, "VALID")
        x = concat(b3, b7, bp)

        # -- 2x inception-C (8x8), mixed9..10 ------------------------------
        for _ in range(2):
            b1 = cb(x, 320, 1, 1)
            b3 = cb(x, 384, 1, 1)
            b3a = cb(b3, 384, 1, 3)
            b3b = cb(b3, 384, 3, 1)
            b3 = concat(b3a, b3b)
            bd = cb(x, 448, 1, 1)
            bd = cb(bd, 384, 3, 3)
            bda = cb(bd, 384, 1, 3)
            bdb = cb(bd, 384, 3, 1)
            bd = concat(bda, bdb)
            bp = avg_pool_keras(x, 3, 1, "SAME")
            bp = cb(bp, 192, 1, 1)
            x = concat(b1, b3, bd, bp)

        features = global_avg_pool(x)
        if not self.include_top:
            return features, None
        logits = self._dense(nm, features, self.num_classes)
        return features, nn.softmax(logits)
