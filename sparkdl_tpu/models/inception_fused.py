"""Branch-merged InceptionV3 eval forward (TPU inference fast path).

Each Inception mixed block runs several 1x1 convs over the SAME input
tensor (branch heads). XLA schedules them as separate convolutions, so the
block input is read from HBM once per branch. This module evaluates the
identical math with the branch-head kernels concatenated along the output
axis — one bigger conv per head group (input read once, larger MXU op),
then a channel split. Weights are the ordinary zoo ``variables``
(models/inception.py construction order); kernels are concatenated at
trace time (tiny, folded by XLA).

Merged groups (all 1x1 stride-1 heads sharing the block input):
  - inception-A x3: b1 / b5-reduce / b3-reduce
  - inception-B x4: b1 / b7-reduce / b7dbl-reduce
  - reduction-B:    b3-reduce / b7-reduce
  - inception-C x2: b1 / b3-reduce / b3dbl-reduce

Eval-only (BatchNorm running stats; training uses the canonical module).
Exactness vs the module is oracle-tested in tests/models/test_fused.py.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sparkdl_tpu.models.common import avg_pool_keras, global_avg_pool, max_pool

_BN_EPS = 1e-3  # models/common.py _bn default, as InceptionV3 uses


def _conv(x, kernel, strides=1, padding="SAME"):
    return lax.conv_general_dilated(
        x, kernel, (strides, strides), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _relu(x):
    return jnp.maximum(x, 0)


class _Flow:
    """Reads conv/bn weights by the module's construction-order index."""

    def __init__(self, variables, dtype):
        self.p = variables["params"]
        self.s = variables["batch_stats"]
        self.dtype = dtype
        self.i = 0

    def take(self, n: int = 1):
        idxs = list(range(self.i, self.i + n))
        self.i += n
        return idxs if n > 1 else idxs[0]

    def kernel(self, i):
        return self.p[f"conv{i:03d}"]["kernel"].astype(self.dtype)

    def bn_consts(self, i):
        """(scale r, shift) for eval BN: y = z*r + shift (scale-free BN)."""
        bn, st = self.p[f"bn{i:03d}"], self.s[f"bn{i:03d}"]
        r = lax.rsqrt(st["var"] + _BN_EPS)
        shift = bn["bias"] - st["mean"] * r
        return r.astype(self.dtype), shift.astype(self.dtype)

    def cbr(self, x, i=None, strides=1, padding="SAME"):
        """conv[i] + eval-BN[i] + relu (i defaults to the next index)."""
        if i is None:
            i = self.take()
        # avg_pool_keras promotes to f32 (its non-pad divisor); keep the
        # compute dtype stable into the conv
        z = _conv(x.astype(self.dtype), self.kernel(i), strides, padding)
        r, shift = self.bn_consts(i)
        return _relu(z * r + shift)

    def merged_heads(self, x, idxs):
        """The 1x1 stride-1 heads ``idxs`` over ``x`` as ONE conv; returns
        per-head outputs (post BN+relu), channel-split."""
        kernels = [self.kernel(i) for i in idxs]
        widths = [k.shape[-1] for k in kernels]
        consts = [self.bn_consts(i) for i in idxs]
        z = _conv(x, jnp.concatenate(kernels, axis=-1))
        r = jnp.concatenate([c[0] for c in consts])
        shift = jnp.concatenate([c[1] for c in consts])
        z = _relu(z * r + shift)
        outs, start = [], 0
        for w in widths:
            outs.append(z[..., start:start + w])
            start += w
        return outs


def fused_inception_v3_features(variables, x, dtype=jnp.bfloat16):
    """2048-d features, identical math to
    ``InceptionV3(include_top=False).apply(variables, x, train=False)``
    with branch heads merged. ``x``: [B, H, W, 3], already preprocessed
    (or raw pixels if the variables were preprocess-folded, ops/fold.py).
    """
    f = _Flow(variables, dtype)
    x = x.astype(dtype)

    # -- stem ----------------------------------------------------------
    x = f.cbr(x, strides=2, padding="VALID")
    x = f.cbr(x, padding="VALID")
    x = f.cbr(x)
    x = max_pool(x, 3, 2, "VALID")
    x = f.cbr(x, padding="VALID")
    x = f.cbr(x, padding="VALID")
    x = max_pool(x, 3, 2, "VALID")

    # -- 3x inception-A (module order: b1, b5r, b5, b3r, b3a, b3b, bp) --
    for _ in range(3):
        idx = f.take(7)
        b1, b5, b3 = f.merged_heads(x, [idx[0], idx[1], idx[3]])
        b5 = f.cbr(b5, idx[2])
        b3 = f.cbr(b3, idx[4])
        b3 = f.cbr(b3, idx[5])
        bp = f.cbr(avg_pool_keras(x, 3, 1, "SAME"), idx[6])
        x = jnp.concatenate([b1, b5, b3, bp], axis=-1)

    # -- reduction-A (b3s2, bdr, bd, bds2 — no mergeable heads) --------
    b3 = f.cbr(x, strides=2, padding="VALID")
    bd = f.cbr(x)
    bd = f.cbr(bd)
    bd = f.cbr(bd, strides=2, padding="VALID")
    x = jnp.concatenate([b3, bd, max_pool(x, 3, 2, "VALID")], axis=-1)

    # -- 4x inception-B (order: b1, b7r, b7a, b7b, bdr, bd1..bd4, bp) --
    for _ in range(4):
        idx = f.take(10)
        b1, b7, bd = f.merged_heads(x, [idx[0], idx[1], idx[4]])
        b7 = f.cbr(b7, idx[2])
        b7 = f.cbr(b7, idx[3])
        bd = f.cbr(bd, idx[5])
        bd = f.cbr(bd, idx[6])
        bd = f.cbr(bd, idx[7])
        bd = f.cbr(bd, idx[8])
        bp = f.cbr(avg_pool_keras(x, 3, 1, "SAME"), idx[9])
        x = jnp.concatenate([b1, b7, bd, bp], axis=-1)

    # -- reduction-B (order: b3r, b3s2, b7r, b7a, b7b, b7s2) -----------
    idx = f.take(6)
    b3, b7 = f.merged_heads(x, [idx[0], idx[2]])
    b3 = f.cbr(b3, idx[1], strides=2, padding="VALID")
    b7 = f.cbr(b7, idx[3])
    b7 = f.cbr(b7, idx[4])
    b7 = f.cbr(b7, idx[5], strides=2, padding="VALID")
    x = jnp.concatenate([b3, b7, max_pool(x, 3, 2, "VALID")], axis=-1)

    # -- 2x inception-C (order: b1, b3r, b3a, b3b, bdr, bd, bda, bdb, bp)
    for _ in range(2):
        idx = f.take(9)
        b1, b3, bd = f.merged_heads(x, [idx[0], idx[1], idx[4]])
        b3 = jnp.concatenate(
            [f.cbr(b3, idx[2]), f.cbr(b3, idx[3])], axis=-1)
        bd = f.cbr(bd, idx[5])
        bd = jnp.concatenate(
            [f.cbr(bd, idx[6]), f.cbr(bd, idx[7])], axis=-1)
        bp = f.cbr(avg_pool_keras(x, 3, 1, "SAME"), idx[8])
        x = jnp.concatenate([b1, b3, bd, bp], axis=-1)

    return global_avg_pool(x).astype(jnp.float32)
