"""VGG16 / VGG19 in Flax (keras.applications.vgg16/vgg19-equivalent).

Named models of the reference (SURVEY.md 2.1). The reference's
DeepImageFeaturizer exposes the fc2 activations (4096-d) as the
transfer-learning features for VGG; we do the same.
"""

from __future__ import annotations

import flax.linen as nn

from sparkdl_tpu.models.common import Namer, ZooModule


class _VGG(ZooModule):
    blocks: tuple[tuple[int, int], ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        nm = Namer()
        for n_convs, filters in self.blocks:
            for _ in range(n_convs):
                x = nn.relu(self._conv(nm, x, filters, 3))
            x = nn.max_pool(x, (2, 2), (2, 2), "VALID")
        # flatten (row-major HWC, matching Keras Flatten)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(self._dense(nm, x, 4096))  # fc1
        features = nn.relu(self._dense(nm, x, 4096))  # fc2 -> featurization layer
        if not self.include_top:
            return features, None
        logits = self._dense(nm, features, self.num_classes)
        return features, nn.softmax(logits)


class VGG16(_VGG):
    blocks: tuple[tuple[int, int], ...] = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))


class VGG19(_VGG):
    blocks: tuple[tuple[int, int], ...] = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))
