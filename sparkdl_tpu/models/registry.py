"""Named-model registry: metadata + constructors for the pretrained zoo.

Parity with the reference's per-model graph/metadata registry (SURVEY.md
2.1): each entry records input size, preprocessing mode, featurization
width, and how to build both the Flax module and the Keras original (for
weight conversion and oracle tests). Weight resolution order:

  1. explicit .h5/.keras file given by the caller,
  2. keras.applications pretrained weights if cached locally
     (zero-egress environments fall back to 3),
  3. random init (weights=None) — architecture-only mode.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    name: str
    flax_builder: Callable[..., Any]
    keras_builder_path: str  # "module:attr" inside keras.applications
    input_size: tuple[int, int]
    preprocess: str  # key into sparkdl_tpu.ops.preprocess.PREPROCESSORS
    feature_dim: int
    num_classes: int = 1000
    #: Keras models whose featurization layer needs the classifier head
    #: built (VGG fc2), i.e. include_top must stay True even for features.
    features_need_top: bool = False
    #: per-type layer ordering for weight conversion (see keras_loader)
    layer_order: str = "topo"
    #: pretrained-weight source: "keras" (keras.applications + the
    #: keras_loader converter) or "hf" (a transformers model through the
    #: family's load_hf_* converter, e.g. models.vit.load_hf_vit)
    source: str = "keras"


def _entries() -> dict[str, ModelEntry]:
    from sparkdl_tpu.models.inception import InceptionV3
    from sparkdl_tpu.models.resnet import ResNet50
    from sparkdl_tpu.models.vgg import VGG16, VGG19
    from sparkdl_tpu.models.xception import Xception

    from sparkdl_tpu.models.vit import vit_b16_builder

    entries = [
        ModelEntry("InceptionV3", InceptionV3, "inception_v3:InceptionV3",
                   (299, 299), "tf", 2048, layer_order="auto_suffix"),
        ModelEntry("Xception", Xception, "xception:Xception",
                   (299, 299), "tf", 2048),
        ModelEntry("ResNet50", ResNet50, "resnet:ResNet50",
                   (224, 224), "caffe", 2048),
        ModelEntry("VGG16", VGG16, "vgg16:VGG16",
                   (224, 224), "caffe", 4096, features_need_top=True),
        ModelEntry("VGG19", VGG19, "vgg19:VGG19",
                   (224, 224), "caffe", 4096, features_need_top=True),
        # beyond-parity: the transformer vision family. HF ViT's default
        # image processing (rescale 1/255, normalize mean=std=0.5) is
        # exactly the "tf" preprocess mode.
        ModelEntry("ViTB16", vit_b16_builder, "",
                   (224, 224), "tf", 768, source="hf"),
    ]
    return {e.name: e for e in entries}


_REGISTRY: dict[str, ModelEntry] | None = None


def registry() -> dict[str, ModelEntry]:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _entries()
    return _REGISTRY


SUPPORTED_MODELS = ("InceptionV3", "Xception", "ResNet50", "VGG16",
                    "VGG19", "ViTB16")


def get_entry(name: str) -> ModelEntry:
    reg = registry()
    if name not in reg:
        raise ValueError(
            f"unknown model {name!r}; supported: {sorted(reg)}"
        )
    return reg[name]


def build_keras_model(entry: ModelEntry, weights: str | None = "imagenet",
                      include_top: bool = True):
    """Build the keras.applications original (for conversion/oracles).

    Falls back to random weights when pretrained ones are not cached and
    cannot be downloaded (zero-egress), with a warning.
    """
    import importlib

    if entry.source != "keras":
        raise ValueError(
            f"model {entry.name} has no keras.applications source "
            f"(source={entry.source!r}); use the family's load_hf_* "
            "converter for pretrained weights"
        )
    mod_name, attr = entry.keras_builder_path.split(":")
    mod = importlib.import_module(f"keras.applications.{mod_name}")
    builder = getattr(mod, attr)
    try:
        return builder(weights=weights, include_top=include_top)
    except Exception as e:
        if weights is not None:
            logger.warning(
                "could not load %s pretrained weights (%s); using random init",
                entry.name, e,
            )
            return builder(weights=None, include_top=include_top)
        raise


def build_flax_model(name: str, weights: "str | None" = "imagenet",
                     dtype=None, include_top: bool = True):
    """Return (module, variables) for a named model.

    ``weights`` may be 'imagenet', a path to a Keras .h5/.keras file, or
    None / 'random' for random init ('random' exists so Spark-ML Param
    plumbing — where None means "unset, use the default" — can still
    request random init explicitly).
    """
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.models.keras_loader import (
        check_variables_match,
        keras_to_flax_variables,
        load_keras_model_file,
        prune_to_structure,
    )

    entry = get_entry(name)
    if weights == "random":
        weights = None
    if dtype is None:
        dtype = jnp.float32
    ktop = include_top or entry.features_need_top
    module = entry.flax_builder(
        include_top=ktop, dtype=dtype, num_classes=entry.num_classes
    )
    if entry.source == "hf" and weights is not None:
        # HF-family pretrained weights load through the family's
        # load_hf_* converter (e.g. models.vit.load_hf_vit on a
        # transformers model instance) — the 'imagenet' shortcut is a
        # keras.applications concept with no loader here. ANY non-None
        # weights (including the 'imagenet' default) fails loudly:
        # silently degrading to random init would hand back garbage
        # features for a model listed in SUPPORTED_MODELS.
        raise ValueError(
            f"model {name} sources pretrained weights from HF — "
            f"weights={weights!r} has no keras.applications loader. "
            "Pass weights='random' (or None) explicitly for random "
            "init, or convert a transformers model via the family's "
            "load_hf_* converter (e.g. models.vit.load_hf_vit)."
        )
    if weights is None:
        h, w = entry.input_size
        variables = module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, h, w, 3), jnp.float32)
        )
        return module, variables
    if isinstance(weights, str) and weights != "imagenet":
        kmodel = load_keras_model_file(weights)
    else:
        kmodel = build_keras_model(entry, weights=weights, include_top=ktop)
    variables = keras_to_flax_variables(kmodel, layer_order=entry.layer_order)
    h, w = entry.input_size
    init_vars = jax.eval_shape(
        lambda: module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, h, w, 3), jnp.float32)
        )
    )
    # weight files may carry a classifier head the module doesn't build
    variables = prune_to_structure(variables, init_vars)
    check_variables_match(variables, init_vars)
    return module, variables
