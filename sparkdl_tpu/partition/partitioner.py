"""The Partitioner: one object that owns every placement decision.

Before this subsystem, sharding decisions were scattered — raw
``NamedSharding`` literals in ``train/finetune.py``, an ad-hoc dp mesh
inside ``BatchedRunner``, device pinning inside ``ReplicaPool`` — and
anything beyond pure data parallelism meant editing all of them. A
:class:`Partitioner` centralizes the decisions behind one surface
(mirroring the ``DataParallelPartitioner``/``SPMDPartitioner`` split of
the exemplar codebases, SNIPPETS [2]):

- **where a batch goes** (:meth:`shard_batch` / :meth:`batch_sharding`),
- **where params and optimizer state live** (:meth:`shard_params` /
  :meth:`shard_opt_state`, specs from the regex rule tables of
  ``partition/rules.py`` and the ZeRO policy of ``partition/zero.py``),
- **how a step is compiled** (:meth:`wrap_step` pins the output state to
  its shardings from *inside* the traced function, so the same wrapped
  step works under plain ``jit`` and under ``chain_carry``'s scan; and
  :meth:`wrap_apply` jits an inference forward with **explicit
  in/out shardings** — the form that dodges the jax 0.4.x implicit-GSPMD
  miscompile the dp+tp GPT oracle documents),
- **how state leaves the mesh** (:meth:`gather_for_checkpoint`).

Implementations:

- :class:`SingleDevicePartitioner` — no mesh; everything on one pinned
  (or the default) device. What a ``ReplicaPool`` executor uses.
- :class:`DataParallelPartitioner` — batch split over the data axes,
  params replicated; ``zero_axis="fsdp"`` additionally ZeRO-shards the
  optimizer state (per-chip opt memory ~1/fsdp, arXiv 2004.13336).
- :class:`SPMDPartitioner` — params placed by a rule table (tp/fsdp),
  batch over the data axes; the general dp × tp × fsdp form.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkdl_tpu.partition.mesh_factory import axis_sizes
from sparkdl_tpu.partition.rules import (
    match_partition_rules,
    tree_path_names,
)
from sparkdl_tpu.partition.zero import (
    export_opt_state_bytes,
    zero_partition_specs,
)
from sparkdl_tpu.runtime.mesh import MeshShapeError, mesh_context

__all__ = [
    "Partitioner",
    "SingleDevicePartitioner",
    "DataParallelPartitioner",
    "SPMDPartitioner",
]


def _unbox(tree: Any) -> Any:
    """Strip flax ``nn.Partitioned`` boxes if flax is in the tree."""
    try:
        from flax.core import meta
    except Exception:  # pragma: no cover - flax is a hard dep in practice
        return tree
    return meta.unbox(tree)


class Partitioner:
    """Base class: a mesh (possibly None), the batch axes, and the spec
    policies. Subclasses override the ``*_specs`` policy hooks; the
    placement/compile mechanics live here once."""

    def __init__(self, mesh: "Mesh | None" = None, *,
                 batch_axes: Sequence[str] = ("dp", "fsdp"),
                 zero_axis: "str | None" = None):
        self.mesh = mesh
        if mesh is not None:
            missing = [a for a in batch_axes if a not in mesh.axis_names]
            if missing:
                raise MeshShapeError(
                    f"batch axes {missing} not in mesh axes "
                    f"{tuple(mesh.axis_names)}"
                )
            if zero_axis is not None and zero_axis not in mesh.axis_names:
                raise MeshShapeError(
                    f"zero_axis {zero_axis!r} not in mesh axes "
                    f"{tuple(mesh.axis_names)}"
                )
        self.batch_axes = tuple(batch_axes)
        self.zero_axis = zero_axis
        # NamedShardings are immutable; cache them per spec so hot paths
        # (one shard_batch per dispatch) never rebuild one
        self._sharding_cache: "dict[P, NamedSharding]" = {}

    # -- spec policy hooks ---------------------------------------------------
    def batch_spec(self) -> P:
        """Leading (batch) dim split over the data axes."""
        return P(self.batch_axes)

    def param_specs(self, params: Any, *, count_hits: bool = False) -> Any:
        """Pytree of ``PartitionSpec`` for the params. Replicated here;
        :class:`SPMDPartitioner` consults its rule table.
        ``count_hits`` lands matches in the rule-hit metric — only
        :meth:`shard_params` (the authoritative placement) sets it, so
        ``sparkdl_partition_rule_hits_total`` counts each placement
        once no matter how many derived views (``wrap_apply``,
        ``param_shardings``) re-ask for the specs."""
        del count_hits
        return jax.tree_util.tree_map(lambda _: P(), _unbox(params))

    def opt_specs(self, opt_state: Any, *, count_hits: bool = False) -> Any:
        """Specs for the optimizer state: the param rules re-matched over
        the state's paths (the state mirrors the param tree), then — with
        ``zero_axis`` set — ZeRO-sharded along that axis wherever still
        replicated (partition/zero.py)."""
        base = self._opt_base_specs(opt_state, count_hits=count_hits)
        if self.zero_axis is None:
            return base
        return zero_partition_specs(
            opt_state, axis=self.zero_axis,
            axis_size=self._axis_size(self.zero_axis), base_specs=base,
        )

    def _opt_base_specs(self, opt_state: Any, *,
                        count_hits: bool = False) -> Any:
        del count_hits
        return jax.tree_util.tree_map(lambda _: P(), opt_state)

    # -- derived shardings ---------------------------------------------------
    def _named(self, spec: P) -> "NamedSharding":
        assert self.mesh is not None
        cached = self._sharding_cache.get(spec)
        if cached is None:
            cached = self._sharding_cache[spec] = NamedSharding(
                self.mesh, spec)
        return cached

    def batch_sharding(self) -> "NamedSharding":
        return self._named(self.batch_spec())

    def chain_batch_sharding(self) -> "NamedSharding":
        """For a stacked ``[K, batch, ...]`` fused-dispatch feed: K is the
        scanned dim (unsharded), batch stays on the data axes."""
        return self._named(P(None, self.batch_axes))

    def replicated_sharding(self) -> "NamedSharding":
        return self._named(P())

    def param_shardings(self, params: Any) -> Any:
        return jax.tree_util.tree_map(
            self._named, self.param_specs(params))

    def opt_shardings(self, opt_state: Any) -> Any:
        return jax.tree_util.tree_map(self._named, self.opt_specs(opt_state))

    # -- placement -----------------------------------------------------------
    def shard_batch(self, arrays: Any, *, check: bool = True) -> Any:
        """Host batch -> device, split over the data axes. Loud on a
        batch dim the mesh cannot divide (the alternative is an XLA
        error naming nothing). ``check=False`` skips the per-leaf walk
        for dispatch paths whose batches are already padded to
        data-axis multiples (BatchedRunner's bucketed feed)."""
        n = self.data_axis_size
        if check and n > 1:
            for name, leaf in tree_path_names(arrays):
                dim = getattr(leaf, "shape", (0,))
                if dim and dim[0] % n != 0:
                    raise MeshShapeError(
                        f"batch leaf {name!r} has leading dim {dim[0]}, "
                        f"not divisible by the {n}-way data axes "
                        f"{self.batch_axes} of the "
                        f"{self.mesh.devices.size}-device mesh"
                    )
        return jax.device_put(arrays, self.batch_sharding())

    @staticmethod
    def _owned_put(tree: Any, shardings: Any) -> Any:
        """Place ``tree`` on ``shardings`` with buffers the RESULT owns.

        Train state is DONATED on the fused-dispatch path (chain_carry),
        and jax 0.4's ``device_put`` aliases same-device shards even
        under ``may_alias=False`` — donation would then delete the
        caller's own arrays. A jitted identity with ``out_shardings``
        always materializes fresh buffers."""
        return jax.jit(lambda t: t, out_shardings=shardings)(tree)

    def shard_params(self, params: Any) -> Any:
        # the one placement that counts rule hits: specs derived ONCE
        # and reused for validation + sharding, so
        # sparkdl_partition_rule_hits_total is one count per placement
        params = _unbox(params)
        specs = self.param_specs(params, count_hits=True)
        self._check_divisible(params, specs, "param")
        return self._owned_put(
            params, jax.tree_util.tree_map(self._named, specs))

    def shard_opt_state(self, opt_state: Any) -> Any:
        specs = self.opt_specs(opt_state, count_hits=True)
        self._check_divisible(opt_state, specs, "opt")
        return self._owned_put(
            opt_state, jax.tree_util.tree_map(self._named, specs))

    def shard_replicated(self, tree: Any) -> Any:
        """Place small fully-replicated leaves (step counters, schedules)."""
        return self._owned_put(tree, jax.tree_util.tree_map(
            lambda _: self.replicated_sharding(), tree))

    def gather_for_checkpoint(self, tree: Any) -> Any:
        """Fully-replicated copy of ``tree`` on the same mesh — what a
        layout-independent checkpoint (or a host export) wants. The
        :class:`~sparkdl_tpu.checkpoint.CheckpointManager` also saves
        sharded trees directly (orbax records the layout); gathering
        first buys a checkpoint any future partitioner can restore
        without resharding metadata."""
        repl = self.replicated_sharding()
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, repl), _unbox(tree))

    # -- compile -------------------------------------------------------------
    def wrap_step(self, step_fn: Callable, state_shardings: Any) -> Callable:
        """``(state, batch) -> (state, aux)`` with the output state
        constrained to ``state_shardings`` from inside the trace.

        The constraint — not ``out_shardings`` — is what keeps ZeRO
        state sharded across steps on every compile path: it survives
        ``jax.jit``, ``chain_carry``'s ``lax.scan``, and donation
        unchanged, because it is part of the traced computation itself.
        """

        def wrapped(state, batch):
            new_state, aux = step_fn(state, batch)
            return (
                lax.with_sharding_constraint(new_state, state_shardings),
                aux,
            )

        return wrapped

    def wrap_apply(self, apply_fn: Callable, params: Any) -> Callable:
        """Jit ``apply_fn(params, batch)`` with **explicit** in/out
        shardings: params on their specs, batch and every output leaf
        split over the data axes.

        Explicitness is load-bearing on jax 0.4.x: the implicit form
        (committed arrays + bare ``jit``) miscompiles dp+tp-sharded
        transformer forwards (PARITY.md repro); spelling the shardings
        on the jit boundary compiles correctly on 0.4.x and 0.5+ both.
        """
        return jax.jit(
            apply_fn,
            in_shardings=(self.param_shardings(_unbox(params)),
                          self.batch_sharding()),
            out_shardings=self.batch_sharding(),
        )

    # -- introspection / context ---------------------------------------------
    def _axis_size(self, axis: str) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape[axis])

    @property
    def data_axis_size(self) -> int:
        """Ways the batch dim is split (1 = no splitting)."""
        n = 1
        for a in self.batch_axes:
            n *= self._axis_size(a)
        return n

    def mesh_context(self):
        if self.mesh is None:
            return contextlib.nullcontext()
        return mesh_context(self.mesh)

    def describe(self) -> "dict[str, Any]":
        """Operator/bench view: kind, axis sizes, batch/zero policy."""
        return {
            "kind": type(self).__name__,
            "axes": axis_sizes(self.mesh),
            "batch_axes": list(self.batch_axes),
            "zero_axis": self.zero_axis,
            "data_axis_size": self.data_axis_size,
        }

    def export_opt_state_bytes(self, opt_state: Any) -> int:
        """Per-chip optimizer-state bytes into the spine
        (``sparkdl_opt_state_bytes{axis=...}``)."""
        return export_opt_state_bytes(opt_state, axis=self.zero_axis)

    # -- validation ----------------------------------------------------------
    def _check_divisible(self, tree: Any, specs: Any, what: str) -> None:
        if self.mesh is None:
            return
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        for (name, leaf), spec in zip(tree_path_names(tree), spec_leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            for i, part in enumerate(spec):
                if part is None or i >= len(shape):
                    continue
                entries = part if isinstance(part, (tuple, list)) else (part,)
                n = 1
                for a in entries:
                    n *= self._axis_size(a)
                if n > 1 and shape[i] % n != 0:
                    raise MeshShapeError(
                        f"{what} leaf {name!r} shape {shape}: dim {i} "
                        f"({shape[i]}) not divisible by the {n}-way "
                        f"{entries} split on the "
                        f"{self.mesh.devices.size}-device mesh"
                    )


class SingleDevicePartitioner(Partitioner):
    """Everything on one device (the given one, or jax's default).

    The degenerate-but-load-bearing case: a :class:`~sparkdl_tpu.serving.
    replicas.ReplicaPool` executor is exactly this — the pool scales by
    replicating single-device partitioners, not by splitting batches."""

    def __init__(self, device: Any = None):
        super().__init__(mesh=None, batch_axes=())
        self.device = device

    def batch_spec(self) -> P:
        return P()

    def _named(self, spec: P) -> Any:
        # no mesh: every derived "sharding" (batch/chain/replicated/param)
        # is the one device — keeps the whole base-class surface
        # (finetune's batch_sharding()/chain_batch_sharding() included)
        # usable instead of tripping the mesh assert
        device = self.device
        if device is None:
            device = jax.local_devices()[0]
        return jax.sharding.SingleDeviceSharding(device)

    def shard_batch(self, arrays: Any, *, check: bool = True) -> Any:
        # plain put: batches are never donated, so aliasing is safe here
        # (params/opt state go through the base class's _owned_put —
        # a device_put-aliased TrainState donated by chain_carry would
        # delete the caller's own arrays)
        if self.device is None:
            return jax.device_put(arrays)
        return jax.device_put(arrays, self.device)

    def gather_for_checkpoint(self, tree: Any) -> Any:
        return _unbox(tree)

    def wrap_step(self, step_fn: Callable,
                  state_shardings: Any = None) -> Callable:
        return step_fn  # nothing to constrain on one device

    def wrap_apply(self, apply_fn: Callable, params: Any) -> Callable:
        jitted = jax.jit(apply_fn)
        if self.device is None:
            return jitted
        return lambda p, batch: jitted(
            jax.device_put(p, self.device), self.shard_batch(batch))

    def describe(self) -> "dict[str, Any]":
        out = super().describe()
        out["device"] = str(self.device) if self.device is not None else None
        return out


class DataParallelPartitioner(Partitioner):
    """Batch over the data axes, params replicated — the reference-parity
    layout, now with an optional ZeRO twist: ``zero_axis="fsdp"`` shards
    the optimizer state (and therefore the weight-update math) along the
    fsdp axis while params stay replicated. Per-chip opt memory drops
    ~fsdp-fold; the update all-gather is XLA's to place and overlap."""

    def __init__(self, mesh: "Mesh | None" = None, *,
                 batch_axes: Sequence[str] = ("dp", "fsdp"),
                 zero_axis: "str | None" = None):
        if mesh is None:
            from sparkdl_tpu.runtime.mesh import data_parallel_mesh

            mesh = data_parallel_mesh()
        super().__init__(mesh, batch_axes=batch_axes, zero_axis=zero_axis)


class SPMDPartitioner(Partitioner):
    """General dp × tp × fsdp: params placed by a regex rule table
    (partition/rules.py), batch over the data axes, optimizer state
    rule-matched the same way (the state's paths contain the param
    paths) plus ZeRO sharding along ``zero_axis`` where replicated."""

    def __init__(self, mesh: Mesh, rules: "Sequence[tuple[str, P]]", *,
                 batch_axes: Sequence[str] = ("dp", "fsdp"),
                 zero_axis: "str | None" = None):
        super().__init__(mesh, batch_axes=batch_axes, zero_axis=zero_axis)
        self.rules = tuple(rules)

    def param_specs(self, params: Any, *, count_hits: bool = False) -> Any:
        return match_partition_rules(
            self.rules, _unbox(params), count_hits=count_hits)

    def _opt_base_specs(self, opt_state: Any, *,
                        count_hits: bool = False) -> Any:
        return match_partition_rules(
            self.rules, opt_state, count_hits=count_hits)

    def describe(self) -> "dict[str, Any]":
        out = super().describe()
        out["n_rules"] = len(self.rules)
        return out
