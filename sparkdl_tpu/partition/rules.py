"""Regex partition rules over flattened param paths.

The single vocabulary every partitioner speaks: an ordered table of
``(regex, PartitionSpec)`` pairs matched against ``/``-joined param-tree
paths, **first match wins** (the ``match_partition_rules`` idiom of the
JAX LLM-training lineage — see SNIPPETS [1]). Scalars and size-1 leaves
are never partitioned; a non-scalar leaf no rule matches is a loud
``PartitionRuleError`` — silent replication of a 10-GB embedding is how
out-of-memory surprises happen on chip, so tables must be exhaustive
(end with an explicit ``(".*", P())`` catch-all when replication *is*
the intent).

Because matching uses ``re.search`` over the joined path, the same table
partitions a bare param tree **and** the optimizer state that mirrors it
(``0/mu/h_0/attn/q_proj/kernel`` still contains
``attn/q_proj/kernel``) — one rule table covers the whole TrainState.

Per-model default tables (GPT/BERT/ViT) put the Megatron tp split on
attention and MLP projections — column-parallel kernels ``[in, out/tp]``,
row-parallel ``[in/tp, out]`` — embeddings on (tp, fsdp), every other
kernel row-sharded on fsdp, and norms/biases replicated. On a mesh where
``tp``/``fsdp`` have size 1 those axes are inert and the specs resolve
to replication, so the tables are safe to apply unconditionally.

Every successful match lands in
``sparkdl_partition_rule_hits_total{rule=...}`` so a bench/operator can
see *which* rules actually shaped the model (bench_train.py embeds the
hit-counts in its JSON line).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from sparkdl_tpu.observability.registry import registry

__all__ = [
    "PartitionRuleError",
    "match_partition_rules",
    "tree_path_names",
    "rule_hit_counts",
    "GPT_RULES",
    "BERT_RULES",
    "VIT_RULES",
    "GENERIC_RULES",
    "KV_POOL_RULES",
    "sequence_activation_spec",
    "default_rules_for",
]

_M_RULE_HITS = registry().counter(
    "sparkdl_partition_rule_hits_total",
    "params matched by each partition rule", labels=("rule",))


class PartitionRuleError(ValueError):
    """A non-scalar param leaf matched no rule in the table."""


def _key_str(k: Any) -> str:
    """One path component as a plain string, across jax key types."""
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def path_name(path: "tuple") -> str:
    """``/``-joined flattened-tree path (``h_0/attn/q_proj/kernel``)."""
    return "/".join(_key_str(k) for k in path)


def tree_path_names(tree: Any) -> "list[tuple[str, Any]]":
    """Flatten ``tree`` to ``[(joined_path, leaf), ...]`` in tree order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_name(p), leaf) for p, leaf in flat]


def match_partition_rules(
    rules: "Sequence[tuple[str, P]]", tree: Any, *,
    count_hits: bool = True,
) -> Any:
    """Pytree of ``PartitionSpec`` for ``tree``, first matching rule wins.

    Scalar / single-element leaves get ``P()`` without consulting the
    table (partitioning a scalar is never meaningful). A non-scalar leaf
    with no matching rule raises :class:`PartitionRuleError` naming the
    param — fail loud, never silently replicate.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in flat:
        name = path_name(path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                if count_hits:
                    _M_RULE_HITS.inc(rule=rule)
                specs.append(spec)
                break
        else:
            raise PartitionRuleError(
                f"no partition rule matched param {name!r} "
                f"(shape {tuple(shape)}); add a rule or an explicit "
                f"('.*', P()) catch-all if replication is intended"
            )
    return jax.tree_util.tree_unflatten(treedef, specs)


def rule_hit_counts() -> "dict[str, float]":
    """``{rule_pattern: hits}`` accumulated so far (registry-sourced)."""
    fam = registry().get("sparkdl_partition_rule_hits_total")
    if fam is None:
        return {}
    return fam.labelled_values("rule")


#: GPT decoder family (models/gpt.py naming). Attention q/k/v and the MLP
#: up-projection are column-parallel (out dim on tp), out_proj and the MLP
#: down-projection row-parallel (in dim on tp) — one psum per block, the
#: Megatron pairing the model's own tp metadata encodes.
GPT_RULES: "tuple[tuple[str, P], ...]" = (
    (r"attn/(q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tp")),
    (r"attn/out_proj/kernel$", P("tp", "fsdp")),
    (r"(^|/)(up|wi)/kernel$", P("fsdp", "tp")),
    (r"(^|/)(down|wo)/kernel$", P("tp", "fsdp")),
    (r"(q_proj|k_proj|v_proj|up|wi)/bias$", P("tp")),
    (r"wte/embedding$", P("tp", "fsdp")),
    (r"wpe/embedding$", P(None, "fsdp")),
    (r"ln_.*/(scale|bias)$", P()),
    (r"kernel$", P("fsdp", None)),
    (r".*", P()),
)

#: BERT encoder family (models/bert.py naming).
BERT_RULES: "tuple[tuple[str, P], ...]" = (
    (r"attention/(query|key|value)/kernel$", P("fsdp", "tp")),
    (r"attention/output_dense/kernel$", P("tp", "fsdp")),
    (r"intermediate/kernel$", P("fsdp", "tp")),
    (r"(query|key|value|intermediate)/bias$", P("tp")),
    (r"layer_\d+/output/kernel$", P("tp", "fsdp")),
    (r"embeddings/.*/embedding$", P("tp", "fsdp")),
    (r"LayerNorm/(scale|bias)$", P()),
    (r"kernel$", P("fsdp", None)),
    (r".*", P()),
)

#: ViT encoder family (models/vit.py naming).
VIT_RULES: "tuple[tuple[str, P], ...]" = (
    (r"attention/(query|key|value)/kernel$", P("fsdp", "tp")),
    (r"attention/output_dense/kernel$", P("tp", "fsdp")),
    (r"intermediate/kernel$", P("fsdp", "tp")),
    (r"(query|key|value|intermediate)/bias$", P("tp")),
    (r"layer_\d+/output/kernel$", P("tp", "fsdp")),
    (r"patch_embed/kernel$", P(None, None, None, "fsdp")),
    (r"(cls_token|pos_embed)", P()),
    (r"layernorm.*/(scale|bias)$", P()),
    (r"kernel$", P("fsdp", None)),
    (r".*", P()),
)

#: Model-agnostic fallback: every kernel row-sharded on fsdp (leading
#: dim; trailing dims unsharded), everything else replicated — the
#: "everything else fsdp/replicated" floor for models without a table.
GENERIC_RULES: "tuple[tuple[str, P], ...]" = (
    (r"embedding$", P(None, "fsdp")),
    (r"kernel$", P("fsdp", None)),
    (r".*", P()),
)

#: Sequence-axis placement for the paged KV BLOCK POOL (ISSUE 13):
#: matched over an ``init_block_pool`` tree, the k/v pool arrays
#: ``[layers, n_blocks, block_size, H, D]`` (and the int8 per-column
#: scale arrays ``[layers, n_blocks, block_size]``) shard their BLOCK
#: axis on ``sp`` — contiguous shards, so virtual block id ``b`` lives
#: on chip ``b // (n_blocks/sp)`` (the mapping
#: ``serving.kv_blocks.SeqShardedBlockPool`` mirrors host-side). ``sp``
#: shards *tokens*, never weights: params stay on the replicated /
#: tp-sharded tables above.
KV_POOL_RULES: "tuple[tuple[str, P], ...]" = (
    (r"(^|/)(k|v)$", P(None, "sp")),
    (r"_scale$", P(None, "sp")),
    (r".*", P()),
)


def sequence_activation_spec(*, ndim: int, seq_dim: int = 1,
                             sp_axis: str = "sp",
                             batch_axes: "Sequence[str]" = ()) -> P:
    """``PartitionSpec`` placing an activation's SEQUENCE dim on the
    ``sp`` mesh axis (and optionally its batch dim on ``batch_axes``) —
    the placement vocabulary for sequence-parallel prefill: token ids
    ``[B, L]`` (``ndim=2``), logits ``[B, L, V]`` (``ndim=3``), or
    per-layer K/V ``[layers, B, L, H, D]`` (``ndim=5, seq_dim=2``).
    Contiguous token shards: chip ``c`` holds columns
    ``[c*L/sp, (c+1)*L/sp)``, the layout the ring/all-gather causal
    masks assume."""
    if not 0 <= seq_dim < ndim:
        raise ValueError(
            f"seq_dim {seq_dim} out of range for ndim {ndim}")
    parts: "list" = [None] * ndim
    if batch_axes:
        parts[0] = tuple(batch_axes)
    parts[seq_dim] = sp_axis
    return P(*parts)

_TABLES = {
    "gpt": GPT_RULES,
    "bert": BERT_RULES,
    "vit": VIT_RULES,
    "generic": GENERIC_RULES,
}


def default_rules_for(model: str) -> "tuple[tuple[str, P], ...]":
    """Rule table for a model family name (``gpt``/``bert``/``vit``),
    :data:`GENERIC_RULES` for anything unrecognized."""
    key = model.lower()
    for name, table in _TABLES.items():
        if name in key:
            return table
    return GENERIC_RULES
