"""ZeRO-style cross-replica sharding of optimizer state.

*Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training* (arXiv 2004.13336) observes that in data-parallel training the
optimizer state — and the weight-update math itself — is computed
identically on every replica, so it can be **sharded** across them and
the updated params all-gathered afterwards: per-chip optimizer memory
drops ~Nx for an N-way shard at the cost of one extra all-gather that
overlaps the step. The jit/GSPMD form needs no manual collectives at
all: place the optimizer-state arrays with a sharded ``NamedSharding``
along the ``fsdp`` axis, constrain the step's output state to the same
sharding (``Partitioner.wrap_step``), and XLA shards the elementwise
update and inserts the gather.

This module owns the spec choice: for each state leaf, shard the
**largest dimension divisible by the axis size** (leaves the rules
already sharded on the axis, scalars, and non-divisible leaves alone),
and the measurement: per-chip state bytes, exported through the
observability spine as ``sparkdl_opt_state_bytes{axis=...}`` so the
memory win is a number on a dashboard, not a belief.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from sparkdl_tpu.observability.registry import registry

__all__ = [
    "zero_leaf_spec",
    "zero_partition_specs",
    "opt_state_bytes_per_chip",
    "export_opt_state_bytes",
]

_M_OPT_BYTES = registry().gauge(
    "sparkdl_opt_state_bytes",
    "per-chip optimizer-state bytes, by sharding axis "
    "('replicated' = no ZeRO sharding)", labels=("axis",))


def zero_leaf_spec(shape: "tuple[int, ...]", *, axis: str, axis_size: int,
                   base: "P | None" = None) -> P:
    """ZeRO spec for one state leaf: ``base`` if it already uses ``axis``,
    else ``base`` with the largest ``axis_size``-divisible unsharded dim
    additionally sharded on ``axis`` (``base`` unchanged when none is —
    a 3-element bias is cheaper replicated than padded)."""
    parts: "list[Any]" = list(base) if base is not None else []
    parts += [None] * (len(shape) - len(parts))
    for p in parts:
        entries = p if isinstance(p, (tuple, list)) else (p,)
        if axis in entries:
            return base if base is not None else P()
    candidates = [
        (dim, i) for i, (dim, p) in enumerate(zip(shape, parts))
        if p is None and dim % axis_size == 0 and dim >= axis_size
    ]
    if not candidates or axis_size <= 1:
        return base if base is not None else P()
    _, best = max(candidates, key=lambda t: (t[0], -t[1]))
    parts[best] = axis
    return P(*parts)


def zero_partition_specs(tree: Any, *, axis: str, axis_size: int,
                         base_specs: Any = None) -> Any:
    """Pytree of ZeRO specs for an optimizer-state (or param) tree.

    ``base_specs`` (same structure, e.g. the rule-matched specs) is
    honored where it already shards a leaf on ``axis``; everywhere else
    the leaf's largest divisible dim is sharded on ``axis``.
    """
    flat, treedef = jax.tree_util.tree_flatten(tree)
    base_flat = (jax.tree_util.tree_flatten(base_specs)[0]
                 if base_specs is not None else [None] * len(flat))
    if len(base_flat) != len(flat):
        raise ValueError(
            f"base_specs has {len(base_flat)} leaves, tree has {len(flat)}"
        )
    specs = []
    for leaf, base in zip(flat, base_flat):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        specs.append(
            zero_leaf_spec(shape, axis=axis, axis_size=axis_size, base=base)
        )
    return jax.tree_util.tree_unflatten(treedef, specs)


def opt_state_bytes_per_chip(tree: Any, device: Any = None) -> int:
    """Bytes of ``tree`` resident on ONE chip.

    For each committed ``jax.Array`` leaf, the size of its shard on
    ``device`` (default: the first local device; a leaf not addressable
    there counts its first addressable shard — every chip of a
    replicated layout holds the same bytes anyway). Uncommitted /
    non-jax leaves count their full host size.
    """
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            total += int(getattr(leaf, "nbytes", 0) or 0)
            continue
        if device is None:
            device = shards[0].device
        chosen = None
        for sh in shards:
            if sh.device == device:
                chosen = sh
                break
        if chosen is None:
            chosen = shards[0]
        total += int(np.prod(chosen.data.shape) * chosen.data.dtype.itemsize)
    return total


def export_opt_state_bytes(tree: Any, *, axis: "str | None") -> int:
    """Measure :func:`opt_state_bytes_per_chip` and land it in the spine
    as ``sparkdl_opt_state_bytes{axis=...}``; returns the bytes."""
    n = opt_state_bytes_per_chip(tree)
    _M_OPT_BYTES.set(n, axis=axis if axis else "replicated")
    return n
