"""dp × tp × fsdp mesh construction with loud validation.

Generalizes ``runtime/mesh.py``'s data-parallel-only builders into the
partitioner subsystem's front door: named keyword axes over the
canonical :data:`~sparkdl_tpu.runtime.mesh.AXIS_ORDER`, at most one
``-1`` axis inferred from the device count, and **typed errors at
construction time** — a non-divisor axis size or duplicate axis name
raises :class:`MeshShapeError` with the device count in the message,
instead of surfacing as an opaque reshape/GSPMD error deep inside the
first jit (the failure mode that motivated this module).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from sparkdl_tpu.runtime.mesh import (
    MeshShapeError,
    MeshSpec,
    resolve_axis_sizes,
)

__all__ = ["MeshShapeError", "make_mesh", "make_custom_mesh", "axis_sizes"]


def make_mesh(*, dp: int = -1, pp: int = 1, fsdp: int = 1, sp: int = 1,
              tp: int = 1, ep: int = 1,
              devices: "Sequence[jax.Device] | None" = None) -> Mesh:
    """Build a mesh over the canonical axes (``dp`` inferred by default).

    >>> make_mesh(dp=4, fsdp=2)          # 8 devices: 4-way dp, 2-way zero
    >>> make_mesh(tp=4)                  # dp inferred = n_devices // 4
    >>> make_mesh(dp=1, sp=2)            # sequence-parallel prefill pair

    Every axis is always present (size-1 axes are inert), so
    ``PartitionSpec``\\ s naming any canonical axis resolve on any mesh
    from this factory. Bad shapes raise :class:`MeshShapeError` naming
    the axis sizes and the device count.
    """
    if devices is None:
        devices = jax.devices()
    sizes = dict(dp=dp, pp=pp, fsdp=fsdp, sp=sp, tp=tp, ep=ep)
    for name, size in sizes.items():
        if not isinstance(size, (int, np.integer)) or (size < 1 and size != -1):
            raise MeshShapeError(
                f"mesh axis {name}={size!r} invalid: sizes are ints >= 1, "
                f"or one -1 to infer from the {len(devices)} devices"
            )
    return MeshSpec(**sizes).build(devices)


def make_custom_mesh(axes: "Sequence[tuple[str, int]]",
                     devices: "Sequence[jax.Device] | None" = None) -> Mesh:
    """Mesh over caller-named axes (non-canonical layouts, tests).

    Validates what ``jax.sharding.Mesh`` would otherwise let fail later:
    duplicate/overlapping axis names, non-positive sizes, and a product
    that does not match the device count all raise
    :class:`MeshShapeError` up front. At most one size may be ``-1``
    (inferred).
    """
    if devices is None:
        devices = jax.devices()
    names = [n for n, _ in axes]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise MeshShapeError(
            f"overlapping mesh axis name(s) {dupes}: each of the "
            f"{len(devices)} devices can sit on an axis only once"
        )
    # -1 inference / size / product validation is runtime.mesh's one
    # implementation (MeshSpec.resolve shares it)
    resolved = resolve_axis_sizes(dict(axes), len(devices))
    arr = np.asarray(devices, dtype=object).reshape(
        tuple(resolved[n] for n in names))
    return Mesh(arr, tuple(names))


def axis_sizes(mesh: "Mesh | None") -> "dict[str, int]":
    """``{axis: size}`` for a mesh (``{}`` for the no-mesh case)."""
    if mesh is None:
        return {}
    return {name: int(mesh.shape[name]) for name in mesh.axis_names}
