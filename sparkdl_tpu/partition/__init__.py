"""General partitioner layer: dp × tp × fsdp over one mesh.

One subsystem owns every sharding decision in the framework:

- ``rules``: regex ``(pattern, PartitionSpec)`` tables over flattened
  param paths, first match wins, unmatched-param fail-loud, with
  per-model defaults (GPT/BERT/ViT) and hit-counts in the metrics spine.
- ``partitioner``: the :class:`Partitioner` surface —
  :class:`SingleDevicePartitioner` (a ReplicaPool executor),
  :class:`DataParallelPartitioner` (dp + optional ZeRO opt-state
  sharding), :class:`SPMDPartitioner` (rule-placed params, explicit
  shardings at every jit boundary).
- ``mesh_factory``: dp × tp × fsdp mesh construction with typed
  :class:`MeshShapeError` validation (device count in the message).
- ``zero``: ZeRO-style optimizer-state sharding policy + per-chip
  memory measurement (``sparkdl_opt_state_bytes{axis}``).

``train/finetune.py``, ``transformers/_inference.py`` (BatchedRunner),
and ``serving/replicas.py`` (ReplicaPool) construct their shardings
exclusively through this layer.
"""

from sparkdl_tpu.partition.mesh_factory import (
    MeshShapeError,
    axis_sizes,
    make_custom_mesh,
    make_mesh,
)
from sparkdl_tpu.partition.partitioner import (
    DataParallelPartitioner,
    Partitioner,
    SPMDPartitioner,
    SingleDevicePartitioner,
)
from sparkdl_tpu.partition.rules import (
    BERT_RULES,
    GENERIC_RULES,
    GPT_RULES,
    VIT_RULES,
    PartitionRuleError,
    default_rules_for,
    match_partition_rules,
    rule_hit_counts,
)
from sparkdl_tpu.partition.zero import (
    export_opt_state_bytes,
    opt_state_bytes_per_chip,
    zero_partition_specs,
)

__all__ = [
    "MeshShapeError",
    "axis_sizes",
    "make_custom_mesh",
    "make_mesh",
    "Partitioner",
    "SingleDevicePartitioner",
    "DataParallelPartitioner",
    "SPMDPartitioner",
    "PartitionRuleError",
    "match_partition_rules",
    "rule_hit_counts",
    "default_rules_for",
    "GPT_RULES",
    "BERT_RULES",
    "VIT_RULES",
    "GENERIC_RULES",
    "zero_partition_specs",
    "opt_state_bytes_per_chip",
    "export_opt_state_bytes",
]
