from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF
from sparkdl_tpu.udf.registry import getUDF, listUDFs, registerUDF

__all__ = ["registerKerasImageUDF", "registerUDF", "getUDF", "listUDFs"]
