"""Process-wide UDF registry.

The reference registers UDFs into the Spark SQL function registry through
the JVM ([U: python/sparkdl/utils/jvmapi.py], SURVEY.md 2.14/2.20). This
framework keeps its own registry so registered functions are usable from
every backend: ``applyUDF`` runs one over any supported DataFrame, and when
a live SparkSession is importable the function is *also* registered with
Spark SQL (pandas UDF) so ``SELECT my_udf(image) FROM t`` works unchanged.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

_LOCK = threading.Lock()
_REGISTRY: dict[str, Callable[[Any], Any]] = {}


def registerUDF(name: str, fn: Callable[[Any], Any], spark_session=None) -> None:
    """Register ``fn`` (one value -> one value) under ``name``.

    Re-registering a name replaces it (matches Spark SQL semantics).
    """
    with _LOCK:
        _REGISTRY[name] = fn
    session = spark_session or _active_spark_session()
    if session is not None:
        _register_with_spark(session, name, fn)


def getUDF(name: str) -> Callable[[Any], Any]:
    with _LOCK:
        if name not in _REGISTRY:
            raise KeyError(
                f"no UDF named {name!r}; registered: {sorted(_REGISTRY)}"
            )
        return _REGISTRY[name]


def listUDFs() -> list[str]:
    with _LOCK:
        return sorted(_REGISTRY)


def applyUDF(name: str, dataset, inputCol: str, outputCol: str):
    """Run a registered UDF over a DataFrame column (any backend)."""
    from sparkdl_tpu.dataframe import transform_partitions

    fn = getUDF(name)

    def partition_fn(rows):
        for r in rows:
            out = dict(r)
            try:
                out[outputCol] = fn(r[inputCol])
            except KeyError:
                raise
            except Exception:
                out[outputCol] = None
            yield out

    return transform_partitions(dataset, partition_fn, [(outputCol, "array<float>")])


def _active_spark_session():
    try:
        from pyspark.sql import SparkSession

        return SparkSession.getActiveSession()
    except Exception:
        return None


def _register_with_spark(session, name: str, fn: Callable) -> None:
    """Best-effort Spark SQL registration (row-at-a-time python UDF)."""
    try:
        from pyspark.sql.functions import udf as spark_udf
        from pyspark.sql.types import ArrayType, FloatType

        wrapped = spark_udf(
            lambda v: [float(x) for x in fn(v)], ArrayType(FloatType())
        )
        session.udf.register(name, wrapped)
    except Exception:  # pragma: no cover - requires a live Spark session
        import logging

        logging.getLogger(__name__).warning(
            "could not register UDF %r with Spark SQL", name, exc_info=True
        )
