"""registerKerasImageUDF — deploy a Keras image model as a SQL-style UDF.

Reference parity (SURVEY.md 2.14/3.5, [U: python/sparkdl/udf/
keras_image_model.py]): compose (image-struct converter ⊕ optional user
preprocessor ⊕ model) into one function and register it under a name, so
``SELECT my_udf(image) FROM t`` scores images. The reference splices three
TF graph pieces into one GraphFunction and registers it JVM-side; here the
composition is a host decode (image struct → RGB array) feeding a single
jitted JAX call (resize → preprocessor → model), registered in the
framework registry (and with Spark SQL when a session is live).
"""

from __future__ import annotations

import functools
from typing import Callable

import numpy as np

from sparkdl_tpu.udf.registry import registerUDF


def registerKerasImageUDF(
    udf_name: str,
    keras_model_or_file,
    preprocessor: "Callable | None" = None,
    spark_session=None,
) -> Callable:
    """Register ``udf_name`` scoring image structs with a Keras model.

    ``keras_model_or_file``: a keras.Model or path to .h5/.keras.
    ``preprocessor``: optional jax-traceable fn batch_f32_rgb -> model input
    (runs on device, fused into the model's XLA program). Returns the
    registered callable (image struct / ndarray -> np.ndarray of floats).
    """
    import keras

    if isinstance(keras_model_or_file, str):
        model = keras.models.load_model(keras_model_or_file, compile=False)
    else:
        model = keras_model_or_file

    in_shape = model.input_shape
    if isinstance(in_shape, list):
        raise ValueError("registerKerasImageUDF requires a single-input model")
    target_hw = None
    if len(in_shape) == 4 and in_shape[1] is not None and in_shape[2] is not None:
        target_hw = (int(in_shape[1]), int(in_shape[2]))

    if keras.backend.backend() == "jax":
        import jax

        trainable = [v.value for v in model.trainable_variables]
        non_trainable = [v.value for v in model.non_trainable_variables]

        @jax.jit
        def _apply(batch):
            x = batch
            if preprocessor is not None:
                x = preprocessor(x)
            y, _ = model.stateless_call(trainable, non_trainable, x, training=False)
            return y
    else:  # pragma: no cover - non-jax Keras backend
        def _apply(batch):
            x = preprocessor(batch) if preprocessor is not None else batch
            return model(x, training=False)

    @functools.wraps(_apply)
    def udf(image) -> np.ndarray:
        from sparkdl_tpu.transformers.named_image import (
            _image_to_rgb_array,
            _resize_host,
        )

        arr = _image_to_rgb_array(image)
        if target_hw is not None:
            arr = _resize_host(arr, target_hw)
        out = np.asarray(_apply(np.asarray(arr, np.float32)[None]))
        return out[0]

    udf.__name__ = udf_name
    registerUDF(udf_name, udf, spark_session=spark_session)
    return udf
