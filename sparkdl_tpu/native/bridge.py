"""Python surface over the native staging library.

- :class:`StagingRing` — fixed-slot producer/consumer ring whose slots are
  stable aligned C allocations (numpy views, zero-copy on the host side).
- :func:`pack_rows` — threaded scatter of N rows into one padded
  [bucket, row_stride] matrix (native memcpy fan-out; numpy fallback).
- :class:`DeviceFeeder` — the double-buffered infeed: a packer thread fills
  ring slots, a transfer thread device_puts each slot and recycles it only
  after the copy lands, the consumer iterates device arrays while the next
  batch is already in flight. This is the TensorFrames-block-feed
  equivalent (SURVEY.md 2.15) in TPU-native form.

Everything degrades to pure Python/numpy when the .so can't be built
(``sparkdl_tpu.native.available()`` tells you which path is live).
"""

from __future__ import annotations

import ctypes
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from sparkdl_tpu.native import _lib


def native_available() -> bool:
    return _lib.available()


#: process-wide feeder telemetry: how many streams rode the native ring vs
#: the python fallback, and batches/bytes through the ring. Read by tests
#: (the "does the hot path actually traverse the ring" proof); the
#: observability registry mirrors (below) are the operator surface —
#: `/metrics` sees DeviceFeeder starvation the same way it already sees
#: prefetch starvation.
FEED_STATS = {
    "ring_streams": 0,
    "fallback_streams": 0,
    "ring_batches": 0,
    "ring_bytes": 0,
}

#: Autotuned suggestions (sparkdl_tpu/ingest): the ring's slot count is
#: fixed per stream (native allocations), so its knob lands here and the
#: NEXT DeviceFeeder stream is built with it; pack threads apply live —
#: every pack_rows call without an explicit n_threads reads the current
#: value. None = untuned defaults.
_TUNED: "dict[str, int | None]" = {"ring_slots": None, "pack_threads": None}

_DEFAULT_PACK_THREADS = 4


def tuned_ring_slots(default: int) -> int:
    """Ring slot count for the next staged stream: the autotuned
    suggestion when one is set, else ``default``."""
    v = _TUNED["ring_slots"]
    return int(v) if v else default


def set_tuned_ring_slots(n: "int | None") -> None:
    _TUNED["ring_slots"] = int(n) if n else None


def tuned_pack_threads() -> int:
    """Threads for the native row-pack memcpy fan-out (live-tunable)."""
    v = _TUNED["pack_threads"]
    return int(v) if v else _DEFAULT_PACK_THREADS


def set_tuned_pack_threads(n: "int | None") -> None:
    _TUNED["pack_threads"] = int(n) if n else None


def pack_knobs():
    """The bridge's process-level autotuner knobs (packer parallelism;
    producer-side: grows when the feed starves the consumer). Ring-slot
    knobs are per-stream and exported by the ingest ``to_device`` stage
    instead."""
    from sparkdl_tpu.ingest.autotune import Knob

    return [Knob(
        name="native.pack_threads",
        get=tuned_pack_threads,
        set=set_tuned_pack_threads,
        lo=1, hi=8,
    )]

_METRICS = None


def _ring_metrics():
    """Lazy registry handles for the staging-ring spine (kept off the
    import path — this module must import without the observability
    package warmed up): (batches counter, bytes counter, slot-wait
    counter [packer blocked on a free slot = the transfer/compute side
    is the bottleneck], consumer-wait histogram [consumer blocked on the
    ring output = infeed starvation, same meaning as
    ``sparkdl_prefetch_consumer_wait_seconds`` on the Python path])."""
    global _METRICS
    if _METRICS is None:
        from sparkdl_tpu.observability.registry import registry

        _METRICS = (
            registry().counter(
                "sparkdl_ring_batches_total",
                "batches staged through the native ring"),
            registry().counter(
                "sparkdl_ring_bytes_total",
                "bytes staged through the native ring"),
            registry().counter(
                "sparkdl_ring_slot_wait_seconds_total",
                "packer time blocked waiting for a free ring slot "
                "(device/transfer side is the bottleneck)"),
            registry().histogram(
                "sparkdl_ring_consumer_wait_seconds",
                "consumer time blocked on the ring output queue "
                "(infeed starvation)"),
        )
    return _METRICS


# ---------------------------------------------------------------------------
# Staging ring
# ---------------------------------------------------------------------------

class StagingRing:
    """FIFO ring of fixed-size staging slots backed by native memory.

    Producer: ``idx = acquire_write(); slot_view(idx)[...] = ...;
    commit_write(idx, n_rows)``. Consumer: ``idx = acquire_read();
    use slot_view(idx); release_read(idx)``. ``close()`` ends the stream;
    readers then drain and get ``None``.
    """

    def __init__(self, slot_bytes: int, n_slots: int = 3):
        l = _lib.lib()
        if l is None:
            raise RuntimeError(
                "native bridge unavailable (build failed or disabled); "
                "use the pure-Python prefetcher instead"
            )
        self._l = l
        self._h = l.sdl_ring_create(slot_bytes, n_slots)
        if not self._h:
            raise MemoryError(f"could not allocate {n_slots}x{slot_bytes} ring")
        self.slot_bytes = slot_bytes
        self.n_slots = n_slots

    def slot_view(self, idx: int) -> np.ndarray:
        ptr = self._l.sdl_ring_slot_ptr(self._h, idx)
        return np.ctypeslib.as_array(ptr, shape=(self.slot_bytes,))

    def acquire_write(self, timeout_s: float = -1.0) -> int | None:
        r = self._l.sdl_ring_acquire_write(self._h, timeout_s)
        return None if r < 0 else int(r)

    def commit_write(self, idx: int, n_rows: int, used_bytes: int = 0) -> None:
        self._l.sdl_ring_commit_write(self._h, idx, n_rows, used_bytes)

    def abort_write(self, idx: int) -> None:
        self._l.sdl_ring_abort_write(self._h, idx)

    def acquire_read(self, timeout_s: float = -1.0) -> int | None:
        """Next committed slot index; None on timeout or end-of-stream
        (distinguish via :meth:`closed`)."""
        r = self._l.sdl_ring_acquire_read(self._h, timeout_s)
        return None if r < 0 else int(r)

    def slot_rows(self, idx: int) -> int:
        return int(self._l.sdl_ring_slot_rows(self._h, idx))

    def slot_used(self, idx: int) -> int:
        return int(self._l.sdl_ring_slot_used(self._h, idx))

    def release_read(self, idx: int) -> None:
        self._l.sdl_ring_release_read(self._h, idx)

    def close(self) -> None:
        self._l.sdl_ring_close(self._h)

    @property
    def closed(self) -> bool:
        return bool(self._l.sdl_ring_closed(self._h))

    def destroy(self) -> None:
        if self._h:
            self._l.sdl_ring_destroy(self._h)
            self._h = None

    def __enter__(self) -> "StagingRing":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        self.destroy()


# ---------------------------------------------------------------------------
# Row packing
# ---------------------------------------------------------------------------

def pack_rows(
    rows: Sequence[np.ndarray],
    *,
    bucket: int | None = None,
    row_stride: int | None = None,
    out: np.ndarray | None = None,
    n_threads: "int | None" = None,
) -> np.ndarray:
    """Pack per-row byte arrays into a padded [bucket, row_stride] uint8
    matrix; rows beyond ``len(rows)`` repeat row 0 (bucketed padding).

    ``out`` may be a preallocated buffer (e.g. a ring ``slot_view`` slice)
    to pack straight into staging memory. ``n_threads`` defaults to the
    live autotuned value (:func:`tuned_pack_threads`).
    """
    if not rows:
        raise ValueError("pack_rows needs at least one row")
    if n_threads is None:
        n_threads = tuned_pack_threads()
    srcs = [np.ascontiguousarray(r).view(np.uint8).reshape(-1) for r in rows]
    n = len(srcs)
    stride = row_stride or max(s.nbytes for s in srcs)
    total = bucket or n
    if total < n:
        raise ValueError(f"bucket {total} < n_rows {n}")
    if out is None:
        out = np.empty(total * stride, np.uint8)
    else:
        out = out.view(np.uint8).reshape(-1)
        if out.nbytes < total * stride:
            raise ValueError("out buffer too small")

    l = _lib.lib()
    if l is None:
        view = out[: total * stride].reshape(total, stride)
        for i in range(total):
            s = srcs[i] if i < n else srcs[0]
            nb = min(s.nbytes, stride)
            view[i, :nb] = s[:nb]
            view[i, nb:] = 0
        return view

    ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) for s in srcs]
    )
    sizes = (ctypes.c_uint64 * n)(*[s.nbytes for s in srcs])
    l.sdl_pack_rows(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ptrs, sizes, n, total, 0, stride, n_threads,
    )
    return out[: total * stride].reshape(total, stride)


def u8_to_f32(src: np.ndarray, scale: float = 1.0, bias: float = 0.0,
              n_threads: int = 4) -> np.ndarray:
    """Threaded uint8 -> float32 affine cast (numpy fallback without lib)."""
    src = np.ascontiguousarray(src, np.uint8)
    l = _lib.lib()
    if l is None:
        return src.astype(np.float32) * scale + bias
    dst = np.empty(src.shape, np.float32)
    l.sdl_u8_to_f32(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        src.size, scale, bias, n_threads,
    )
    return dst


# ---------------------------------------------------------------------------
# Double-buffered device feeder
# ---------------------------------------------------------------------------

class DeviceFeeder:
    """Iterate device arrays from a host batch stream with full overlap.

    Pipeline: packer thread (host assembly into ring slots) -> transfer
    thread (device_put from stable slot memory; slot recycled only after
    the transfer completes) -> consumer (this iterator). With n_slots >= 2
    the host is packing batch i+2 while batch i+1 is on the wire and batch
    i is computing: the double-buffered infeed.

    ``batches``: yields either np.ndarray (single-tensor feed) or
    dict[str, np.ndarray] with a FIXED key set (struct-of-tensors feed —
    text's input_ids+attention_mask and multi-input ingested graphs). A
    dict batch occupies one slot with a fixed byte segment per key, so
    the whole struct rides one ring transaction; the iterator then yields
    dicts of device arrays. Shapes may vary in the leading dim only.
    ``transfer`` defaults to jax.device_put (pass a sharded device_put
    for multi-chip feeds). ``max_batch_bytes`` bounds slot segment sizes:
    an int for array feeds, a per-key dict for struct feeds.
    """

    def __init__(
        self,
        batches: "Iterable[np.ndarray | dict[str, np.ndarray]]",
        *,
        n_slots: int = 3,
        transfer: Callable[[np.ndarray], Any] | None = None,
        max_batch_bytes: "int | dict[str, int] | None" = None,
    ):
        self._batches = batches
        self._n_slots = n_slots
        self._transfer = transfer
        self._max_bytes = max_batch_bytes

    def __iter__(self) -> Iterator[Any]:
        import jax

        transfer = self._transfer or jax.device_put
        it = iter(self._batches)
        try:
            first = next(it)
        except StopIteration:
            return

        # normalize both feed forms onto the struct layout: an array feed
        # is a one-key struct that unwraps on yield
        is_struct = isinstance(first, dict)
        if self._max_bytes is not None and is_struct != isinstance(
                self._max_bytes, dict):
            raise TypeError(
                "max_batch_bytes must match the feed form: a dict of "
                "per-key byte caps for dict feeds, an int for array "
                f"feeds (got {type(self._max_bytes).__name__} for a "
                f"{'dict' if is_struct else 'array'} feed)"
            )
        if is_struct:
            keys = list(first)
            first = {k: np.ascontiguousarray(first[k]) for k in keys}
            seg = dict(self._max_bytes or {})
            for k in keys:
                seg.setdefault(k, first[k].nbytes)
        else:
            keys = ["__array__"]
            first = {"__array__": np.ascontiguousarray(first)}
            seg = {"__array__": (self._max_bytes
                                 if self._max_bytes is not None
                                 else first["__array__"].nbytes)}
        offsets = {}
        off = 0
        for k in keys:
            offsets[k] = off
            off += seg[k]
        slot_bytes = off

        def as_struct(b):
            if is_struct:
                missing = [k for k in keys if k not in b]
                if missing:
                    raise ValueError(f"feed batch missing key(s) {missing}")
                return {k: np.ascontiguousarray(b[k]) for k in keys}
            return {"__array__": np.ascontiguousarray(b)}

        def unwrap(d):
            return d if is_struct else d["__array__"]

        if not native_available():
            FEED_STATS["fallback_streams"] += 1
            # Pure-Python path: same overlap via the prefetch queue.
            from sparkdl_tpu.runtime.prefetch import prefetch_to_device

            def chain():
                yield unwrap(first)
                for b in it:
                    yield b

            # size must stay >=1: Queue(maxsize=0) is UNbounded, the
            # opposite of the tight buffering n_slots=1 asks for.
            yield from prefetch_to_device(chain(),
                                          size=max(1, self._n_slots - 1),
                                          transfer=transfer)
            return

        ring = StagingRing(slot_bytes, self._n_slots)
        FEED_STATS["ring_streams"] += 1
        meta: dict[int, dict] = {}  # slot idx -> {key: (shape, dtype)}
        out_q: queue.Queue = queue.Queue(maxsize=self._n_slots)
        stop = threading.Event()
        errors: list[BaseException] = []
        SENTINEL = object()

        ring_batches_m, ring_bytes_m, slot_wait_m, consumer_wait_m = (
            _ring_metrics())

        def packer():
            try:
                for raw in self._chain(first, it):
                    batch = as_struct(raw) if raw is not first else first
                    total = 0
                    for k in keys:
                        if batch[k].nbytes > seg[k]:
                            raise ValueError(
                                f"feed {k!r} of {batch[k].nbytes}B exceeds "
                                f"its slot segment {seg[k]}B. Segments are "
                                "fixed up front (from max_batch_bytes, "
                                "else the FIRST batch's bytes), so no "
                                "later batch may be larger — size "
                                "max_batch_bytes for the largest batch, "
                                "or for variable-sized rows use the "
                                "Python feed path (ragged_rows=True on "
                                "BatchedRunner feeds)."
                            )
                        total += batch[k].nbytes
                    idx = ring.acquire_write(timeout_s=0.0)
                    if idx is None:
                        # no free slot: the transfer/compute side is
                        # behind — meter the stall so it shows in
                        # /metrics next to prefetch producer blocking
                        blocked_from = time.monotonic()
                        while idx is None and not stop.is_set():
                            idx = ring.acquire_write(timeout_s=0.1)
                        slot_wait_m.inc(time.monotonic() - blocked_from)
                    if idx is None:
                        return
                    view = ring.slot_view(idx)
                    for k in keys:
                        o = offsets[k]
                        view[o:o + batch[k].nbytes] = (
                            batch[k].view(np.uint8).reshape(-1))
                    meta[idx] = {
                        k: (batch[k].shape, batch[k].dtype) for k in keys
                    }
                    ring.commit_write(
                        idx, batch[keys[0]].shape[0],
                        offsets[keys[-1]] + batch[keys[-1]].nbytes,
                    )
                    FEED_STATS["ring_batches"] += 1
                    FEED_STATS["ring_bytes"] += total
                    ring_batches_m.inc()
                    ring_bytes_m.inc(total)
            except BaseException as e:
                errors.append(e)
            finally:
                ring.close()

        # On CPU backends jax.device_put is zero-copy for aligned numpy
        # arrays — the "device" array would alias the slot and be corrupted
        # when the slot recycles. Accelerators copy to HBM, so the slot can
        # be released once the transfer lands.
        needs_copy = jax.default_backend() == "cpu"

        def transferrer():
            try:
                while not stop.is_set():
                    idx = ring.acquire_read(timeout_s=0.1)
                    if idx is None:
                        if ring.closed:
                            break
                        continue
                    m = meta.pop(idx)
                    view = ring.slot_view(idx)
                    host = {}
                    for k in keys:
                        shape, dtype = m[k]
                        nbytes = int(np.prod(shape)) * dtype.itemsize
                        o = offsets[k]
                        host[k] = view[o:o + nbytes].view(dtype).reshape(shape)
                    if needs_copy:
                        host = {k: np.array(v, copy=True)
                                for k, v in host.items()}
                    arr = transfer(unwrap(host))
                    # The slot must stay stable until the device copy is
                    # done; block on THIS thread (the consumer keeps
                    # computing meanwhile), then recycle the slot.
                    jax.block_until_ready(arr)
                    ring.release_read(idx)
                    while not stop.is_set():
                        try:
                            out_q.put(arr, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:
                errors.append(e)
            finally:
                # Blocking put: the consumer is draining the queue, so this
                # succeeds; if the consumer abandoned (stop set), give up —
                # never steal queued results to make room.
                while True:
                    try:
                        out_q.put(SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        if stop.is_set():
                            break

        t1 = threading.Thread(target=packer, daemon=True)
        t2 = threading.Thread(target=transferrer, daemon=True)
        t1.start()
        t2.start()
        try:
            while True:
                t_wait = time.monotonic()
                item = out_q.get()
                # consumer blocked on the feed = infeed starvation, the
                # ring-path twin of sparkdl_prefetch_consumer_wait_seconds
                consumer_wait_m.observe(time.monotonic() - t_wait)
                if item is SENTINEL:
                    if errors:
                        raise errors[0]
                    return
                yield item
        finally:
            stop.set()
            ring.close()
            t1.join(timeout=5)
            t2.join(timeout=5)
            ring.destroy()

    @staticmethod
    def _chain(first, rest):
        yield first
        yield from rest
