"""Native JPEG/PNG decode + resize (ctypes over csrc/sdl_decode.cc).

Host-side image ingest without a Python-loop hot path: the reference does
this work in the executor JVM (SURVEY.md 2.2, java.awt decode/resize
feeding TensorFrames); here it is libjpeg/libpng + threads behind a C ABI,
with ``imageIO.PIL_decode_bytes`` as the pure-Python fallback when the
library cannot build.

Resize sampling matches ``jax.image.resize(method="bilinear")``
(half-pixel centers), so decoding at the model's input size on the host
equals decoding native-size and resizing on device.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from sparkdl_tpu.native import _lib


def available() -> bool:
    return _lib.decode_available()


def _info_from_buf(lib, buf, n) -> "tuple[int, int, int] | None":
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    ch = ctypes.c_int32()
    rc = lib.sdl_image_info(
        buf, n, ctypes.byref(h), ctypes.byref(w), ctypes.byref(ch)
    )
    return (h.value, w.value, ch.value) if rc == 0 else None


def image_info(raw: bytes) -> "tuple[int, int, int] | None":
    """(height, width, source channels) from the header; None if not a
    known format. Channels describe the FILE (1 grayscale / 3 RGB /
    4 RGBA); :func:`decode_resize` always emits 3-channel RGB."""
    lib = _lib.decode_lib()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw)
    return _info_from_buf(lib, buf, len(raw))


def decode_resize(raw: bytes, height: "int | None" = None,
                  width: "int | None" = None) -> "np.ndarray | None":
    """Decode one JPEG/PNG to RGB uint8 [H, W, 3]; None on failure.

    Without height/width, decodes at native size (header probe first);
    specifying only one of the two is a misuse and raises.
    """
    if (height is None) != (width is None):
        raise ValueError(
            "pass both height and width, or neither (native size); got "
            f"height={height}, width={width}"
        )
    lib = _lib.decode_lib()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw)  # one copy only
    if height is None:
        info = _info_from_buf(lib, buf, len(raw))
        if info is None:
            return None
        height, width, _ = info
    out = np.empty((height, width, 3), np.uint8)
    rc = lib.sdl_decode_resize(
        buf, len(raw), height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out if rc == 0 else None


def decode_resize_batch(
    raws: "list[bytes]", height: int, width: int,
    n_threads: "int | None" = None,
) -> "tuple[np.ndarray, np.ndarray]":
    """Threaded batch decode into [N, height, width, 3] RGB uint8.

    Returns (batch, statuses); statuses[i] == 0 marks a good row, failed
    rows are zeroed. Raises RuntimeError when the native lib is missing —
    callers choose their own fallback (this is the hot path; silently
    degrading to a Python loop would hide a deployment problem).
    """
    lib = _lib.decode_lib()
    if lib is None:
        raise RuntimeError(
            "native decode library unavailable; use imageIO.PIL_decode_bytes"
        )
    n = len(raws)
    out = np.zeros((n, height, width, 3), np.uint8)
    statuses = np.zeros(n, np.int32)
    if n == 0:
        return out, statuses
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    bufs = [(ctypes.c_uint8 * len(r)).from_buffer_copy(r) for r in raws]
    ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_uint8)) for b in bufs]
    )
    lens = (ctypes.c_uint64 * n)(*[len(r) for r in raws])
    lib.sdl_decode_resize_batch(
        n, ptrs, lens, height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads, statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out, statuses
