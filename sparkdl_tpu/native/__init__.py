"""Native (C++) host runtime: staging ring, row packing, device feeder,
image decode.

The reference's native layer is the TensorFrames JNI bridge + Horovod core
(SURVEY.md 2.15/2.16) — JVM-centric machinery for getting DataFrame blocks
into TF sessions and gradients across GPUs. The TPU equivalents split
differently: gradient comm belongs to XLA/ICI (nothing to hand-write), so
the native surface that matters is the *host side of the infeed* — stable
staging memory, threaded batch assembly, transfer/compute overlap, and
JPEG/PNG decode+resize (the work the reference's in-JVM ImageUtils does,
SURVEY.md 2.2). That is what this package provides, as ctypes-bound C++
libraries with pure-Python fallbacks (same API, lower throughput) when no
toolchain is present.
"""

from sparkdl_tpu.native._lib import available
from sparkdl_tpu.native import arrow, decode
from sparkdl_tpu.native.bridge import (
    DeviceFeeder,
    StagingRing,
    pack_rows,
    u8_to_f32,
)

__all__ = ["available", "arrow", "decode", "DeviceFeeder", "StagingRing", "pack_rows",
           "u8_to_f32"]
