"""Arrow RecordBatch adapters for the staging bridge.

Spark executors hand Python workers **Arrow** batches (mapInArrow /
mapInPandas); the reference's TensorFrames bridge consumed exactly that
interchange on the JVM side (SURVEY.md 2.15). These adapters complete the
native path here: column buffers are exposed to the C++ row packer as
zero-copy numpy views — one threaded scatter from Arrow memory into
staging memory, no per-row Python conversion.

Supported column shapes (the DataFrame feature-column contract):
- primitive (float32/64, ints)            -> [n, 1] matrix
- fixed_size_list<primitive>              -> [n, k] matrix (zero-copy)
- list / large_list <primitive> (ragged)  -> per-row views of the flat
  values buffer, ready for ``pack_rows`` bucketed padding

Null entries are rejected loudly: a null in a feature column is a data
bug, and silently zero-filling it would hide that.
"""

from __future__ import annotations

import numpy as np

from sparkdl_tpu.native.bridge import pack_rows


def _require_pa():
    import pyarrow as pa

    return pa


def _no_nulls(arr, col: str) -> None:
    if arr.null_count:
        raise ValueError(
            f"column {col!r} has {arr.null_count} null rows; feature "
            "columns must be non-null"
        )


def _flat_values(values, start: int, length: int) -> np.ndarray:
    """Zero-copy numpy view of a primitive Arrow array slice."""
    return values.slice(start, length).to_numpy(zero_copy_only=True)


def column_rows(batch, col: str) -> list[np.ndarray]:
    """Per-row numpy views of ``batch[col]`` — no per-row buffer copies.

    Ragged list columns yield rows of their natural lengths; use
    :func:`pack_arrow_column` to scatter them into a padded matrix.
    """
    pa = _require_pa()
    arr = batch.column(col)
    _no_nulls(arr, col)
    t = arr.type
    n = len(arr)
    if pa.types.is_fixed_size_list(t):
        m = column_matrix(batch, col)
        return [m[i] for i in range(n)]
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        # .offsets is already windowed to the slice (length n+1) but its
        # values stay absolute into the full child buffer.
        offsets = arr.offsets.to_numpy()
        values = _flat_values(arr.values, 0, len(arr.values))
        return [values[offsets[i]: offsets[i + 1]] for i in range(n)]
    # primitive column -> one scalar per row
    return list(arr.to_numpy(zero_copy_only=True).reshape(n, 1))


def column_matrix(batch, col: str) -> np.ndarray:
    """Zero-copy [n_rows, width] matrix for a fixed-width column.

    Works for primitive columns (width 1) and fixed_size_list columns;
    ragged list columns raise (pack them via :func:`pack_arrow_column`).
    """
    pa = _require_pa()
    arr = batch.column(col)
    _no_nulls(arr, col)
    t = arr.type
    n = len(arr)
    if pa.types.is_fixed_size_list(t):
        k = t.list_size
        # Null-check only the window this slice actually reads — null rows
        # outside it are someone else's rows.
        _no_nulls(arr.values.slice(arr.offset * k, n * k), col)
        flat = _flat_values(arr.values, arr.offset * k, n * k)
        return flat.reshape(n, k)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        raise ValueError(
            f"column {col!r} is a variable-length list; use "
            "pack_arrow_column for ragged rows"
        )
    return arr.to_numpy(zero_copy_only=True).reshape(n, 1)


def pack_arrow_column(
    batch,
    col: str,
    *,
    bucket: int | None = None,
    row_stride: int | None = None,
    out: np.ndarray | None = None,
    n_threads: int = 4,
) -> tuple[np.ndarray, int, int]:
    """Scatter ``batch[col]`` into a padded [bucket, row_stride] uint8
    staging matrix via the threaded C++ packer.

    Returns (packed, n_rows, row_stride_bytes). ``out`` may be a staging
    ring slot view — Arrow memory then flows straight into pinned staging
    with one copy total. Fixed-width columns take a bulk-copy fast path
    (one contiguous copy); ragged lists go through the threaded C++
    row scatter.
    """
    pa = _require_pa()
    t = batch.column(col).type
    fixed = not (pa.types.is_list(t) or pa.types.is_large_list(t))
    if fixed:
        m = column_matrix(batch, col)
        n = m.shape[0]
        if n == 0:
            raise ValueError(f"column {col!r} has no rows")
        row_bytes = m.shape[1] * m.itemsize
        stride = row_stride or row_bytes
        if stride < row_bytes:
            raise ValueError(f"row_stride {stride} < row bytes {row_bytes}")
        total = bucket or n
        if total < n:
            raise ValueError(f"bucket {total} < n_rows {n}")
        if out is None:
            out = np.empty(total * stride, np.uint8)
        else:
            out = out.view(np.uint8).reshape(-1)
            if out.nbytes < total * stride:
                raise ValueError("out buffer too small")
        view = out[: total * stride].reshape(total, stride)
        flat = np.ascontiguousarray(m).view(np.uint8).reshape(n, row_bytes)
        view[:n, :row_bytes] = flat
        if stride > row_bytes:
            view[:n, row_bytes:] = 0
        view[n:] = view[0]  # bucketed padding repeats row 0 (pack_rows contract)
        return view, n, stride  # [bucket, stride], same shape pack_rows returns

    rows = column_rows(batch, col)
    if not rows:
        raise ValueError(f"column {col!r} has no rows")
    stride = row_stride or max(r.nbytes for r in rows)
    packed = pack_rows(
        rows, bucket=bucket, row_stride=stride, out=out, n_threads=n_threads
    )
    return packed, len(rows), stride
