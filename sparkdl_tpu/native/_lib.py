"""Build-on-first-import loader + ctypes signatures for libsdlbridge.

No pybind11 in the image, so the binding layer is ctypes over a plain C
ABI (see csrc/sdl_bridge.cc). The .so is compiled lazily with g++ and
cached under ``_build/``; environments without a toolchain simply get
``lib() -> None`` and the pure-Python fallbacks in bridge.py take over.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csrc", "sdl_bridge.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "libsdlbridge.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # per-process tmp name: concurrent first imports (several executor
    # processes on one host) must not write through the same tmp inode;
    # whichever os.replace lands last wins, both are valid builds.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)  # atomic publish
        return True
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.warning(
            "sdl_bridge native build failed (%s); using pure-Python staging. %s",
            e, detail.decode(errors="replace")[:500],
        )
        return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.sdl_ring_create.restype = c.c_void_p
    lib.sdl_ring_create.argtypes = [c.c_uint64, c.c_uint32]
    lib.sdl_ring_destroy.argtypes = [c.c_void_p]
    lib.sdl_ring_slot_bytes.restype = c.c_uint64
    lib.sdl_ring_slot_bytes.argtypes = [c.c_void_p]
    lib.sdl_ring_n_slots.restype = c.c_uint32
    lib.sdl_ring_n_slots.argtypes = [c.c_void_p]
    lib.sdl_ring_slot_ptr.restype = c.POINTER(c.c_uint8)
    lib.sdl_ring_slot_ptr.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_acquire_write.restype = c.c_int64
    lib.sdl_ring_acquire_write.argtypes = [c.c_void_p, c.c_double]
    lib.sdl_ring_commit_write.argtypes = [c.c_void_p, c.c_uint32, c.c_uint64, c.c_uint64]
    lib.sdl_ring_abort_write.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_acquire_read.restype = c.c_int64
    lib.sdl_ring_acquire_read.argtypes = [c.c_void_p, c.c_double]
    lib.sdl_ring_slot_rows.restype = c.c_uint64
    lib.sdl_ring_slot_rows.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_slot_used.restype = c.c_uint64
    lib.sdl_ring_slot_used.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_release_read.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_close.argtypes = [c.c_void_p]
    lib.sdl_ring_closed.restype = c.c_int
    lib.sdl_ring_closed.argtypes = [c.c_void_p]
    lib.sdl_pack_rows.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_uint64), c.c_uint64, c.c_uint64, c.c_uint64,
        c.c_uint64, c.c_uint32,
    ]
    lib.sdl_u8_to_f32.argtypes = [
        c.POINTER(c.c_float), c.POINTER(c.c_uint8), c.c_uint64,
        c.c_float, c.c_float, c.c_uint32,
    ]
    return lib


def lib() -> ctypes.CDLL | None:
    """The loaded native library, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SPARKDL_TPU_DISABLE_NATIVE"):
            logger.info("native bridge disabled via SPARKDL_TPU_DISABLE_NATIVE")
            return None
        # Rebuild when the cached .so predates the source (git pull with a
        # persisting _build/), not only when it is absent. A deployment may
        # ship the prebuilt .so without csrc/ — a missing source is simply
        # "not stale", never an error.
        try:
            stale = (
                os.path.exists(_SO)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
        except OSError:
            stale = False
        if (not os.path.exists(_SO) or stale) and not _compile():
            if not os.path.exists(_SO):
                return None  # no cached build to fall back to
        try:
            _lib = _declare(ctypes.CDLL(_SO))
        except (OSError, AttributeError) as e:
            # OSError: corrupt/foreign .so. AttributeError: a cached build
            # missing a newer export — either way fall back to pure Python
            # instead of letting the error escape into every batch assembly.
            logger.warning("could not load %s: %s", _SO, e)
            _lib = None
        return _lib


def available() -> bool:
    return lib() is not None
