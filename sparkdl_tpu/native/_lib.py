"""Build-on-first-import loaders + ctypes signatures for the native libs.

No pybind11 in the image, so the binding layer is ctypes over a plain C
ABI. Each .so is compiled lazily with g++ and cached under ``_build/``;
environments without a toolchain (or without a lib's link dependencies)
simply get ``lib() -> None`` for that library and the pure-Python
fallbacks take over — the staging ring (csrc/sdl_bridge.cc) and the image
decoder (csrc/sdl_decode.cc, links libjpeg/libpng) fail independently.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Callable, Sequence

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")


class NativeLib:
    """One lazily-built native library: compile, cache, declare, fall back."""

    def __init__(self, name: str, source: str,
                 declare: Callable[[ctypes.CDLL], ctypes.CDLL],
                 link_flags: Sequence[str] = ()):
        self._name = name
        self._src = os.path.join(_HERE, "csrc", source)
        self._so = os.path.join(_BUILD_DIR, f"lib{name}.so")
        self._declare = declare
        self._link_flags = list(link_flags)
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._tried = False

    def _compile(self) -> bool:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # per-process tmp name: concurrent first imports (several executor
        # processes on one host) must not write through the same tmp inode;
        # whichever os.replace lands last wins, both are valid builds.
        tmp = f"{self._so}.tmp.{os.getpid()}"
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-o", tmp, self._src, *self._link_flags,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, self._so)  # atomic publish
            return True
        except (OSError, subprocess.SubprocessError) as e:
            detail = getattr(e, "stderr", b"") or b""
            logger.warning(
                "%s native build failed (%s); using pure-Python fallback. %s",
                self._name, e, detail.decode(errors="replace")[:500],
            )
            return False

    def lib(self) -> ctypes.CDLL | None:
        """The loaded library, building it if needed; None if unavailable."""
        if self._lib is not None or self._tried:
            return self._lib
        with self._lock:
            if self._lib is not None or self._tried:
                return self._lib
            self._tried = True
            if os.environ.get("SPARKDL_TPU_DISABLE_NATIVE"):
                logger.info(
                    "%s disabled via SPARKDL_TPU_DISABLE_NATIVE", self._name
                )
                return None
            # Rebuild when the cached .so predates the source (git pull with
            # a persisting _build/), not only when it is absent. A deployment
            # may ship the prebuilt .so without csrc/ — a missing source is
            # simply "not stale", never an error.
            try:
                stale = (
                    os.path.exists(self._so)
                    and os.path.getmtime(self._so) < os.path.getmtime(self._src)
                )
            except OSError:
                stale = False
            if (not os.path.exists(self._so) or stale) and not self._compile():
                if not os.path.exists(self._so):
                    return None  # no cached build to fall back to
            try:
                self._lib = self._declare(ctypes.CDLL(self._so))
            except (OSError, AttributeError) as e:
                # OSError: corrupt/foreign .so. AttributeError: a cached
                # build missing a newer export — either way fall back to
                # pure Python instead of erroring in every batch assembly.
                logger.warning("could not load %s: %s", self._so, e)
                self._lib = None
            return self._lib

    def available(self) -> bool:
        return self.lib() is not None


def _declare_bridge(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.sdl_ring_create.restype = c.c_void_p
    lib.sdl_ring_create.argtypes = [c.c_uint64, c.c_uint32]
    lib.sdl_ring_destroy.argtypes = [c.c_void_p]
    lib.sdl_ring_slot_bytes.restype = c.c_uint64
    lib.sdl_ring_slot_bytes.argtypes = [c.c_void_p]
    lib.sdl_ring_n_slots.restype = c.c_uint32
    lib.sdl_ring_n_slots.argtypes = [c.c_void_p]
    lib.sdl_ring_slot_ptr.restype = c.POINTER(c.c_uint8)
    lib.sdl_ring_slot_ptr.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_acquire_write.restype = c.c_int64
    lib.sdl_ring_acquire_write.argtypes = [c.c_void_p, c.c_double]
    lib.sdl_ring_commit_write.argtypes = [c.c_void_p, c.c_uint32, c.c_uint64, c.c_uint64]
    lib.sdl_ring_abort_write.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_acquire_read.restype = c.c_int64
    lib.sdl_ring_acquire_read.argtypes = [c.c_void_p, c.c_double]
    lib.sdl_ring_slot_rows.restype = c.c_uint64
    lib.sdl_ring_slot_rows.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_slot_used.restype = c.c_uint64
    lib.sdl_ring_slot_used.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_release_read.argtypes = [c.c_void_p, c.c_uint32]
    lib.sdl_ring_close.argtypes = [c.c_void_p]
    lib.sdl_ring_closed.restype = c.c_int
    lib.sdl_ring_closed.argtypes = [c.c_void_p]
    lib.sdl_pack_rows.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_uint64), c.c_uint64, c.c_uint64, c.c_uint64,
        c.c_uint64, c.c_uint32,
    ]
    lib.sdl_u8_to_f32.argtypes = [
        c.POINTER(c.c_float), c.POINTER(c.c_uint8), c.c_uint64,
        c.c_float, c.c_float, c.c_uint32,
    ]
    return lib


def _declare_decode(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.sdl_image_info.restype = c.c_int32
    lib.sdl_image_info.argtypes = [
        c.POINTER(c.c_uint8), c.c_uint64,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_int32),
    ]
    lib.sdl_decode_resize.restype = c.c_int32
    lib.sdl_decode_resize.argtypes = [
        c.POINTER(c.c_uint8), c.c_uint64, c.c_int32, c.c_int32,
        c.POINTER(c.c_uint8),
    ]
    lib.sdl_decode_resize_batch.restype = None
    lib.sdl_decode_resize_batch.argtypes = [
        c.c_uint64, c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_uint64), c.c_int32, c.c_int32,
        c.POINTER(c.c_uint8), c.c_int32, c.POINTER(c.c_int32),
    ]
    return lib


_BRIDGE = NativeLib("sdlbridge", "sdl_bridge.cc", _declare_bridge)
_DECODE = NativeLib("sdldecode", "sdl_decode.cc", _declare_decode,
                    link_flags=("-ljpeg", "-lpng"))


def lib() -> ctypes.CDLL | None:
    """The staging-bridge library (back-compat name)."""
    return _BRIDGE.lib()


def available() -> bool:
    return _BRIDGE.available()


def decode_lib() -> ctypes.CDLL | None:
    return _DECODE.lib()


def decode_available() -> bool:
    return _DECODE.available()
