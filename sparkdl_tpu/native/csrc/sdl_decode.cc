// sdl_decode: native JPEG/PNG decode + bilinear resize for the image
// ingest path.
//
// The reference decodes and resizes images inside the executor JVM
// (SURVEY.md 2.2 — ImageUtils via java.awt, feeding TensorFrames); this is
// the same capability native to this framework: libjpeg/libpng decode with
// a threaded batch API so a partition of image files becomes one padded
// uint8 [N, H, W, 3] block without a Python-loop in the hot path. Kept as
// a separate .so from sdl_bridge so a toolchain without the image
// libraries still builds the staging ring (each loader fails independently
// and Python falls back to PIL).
//
// Resize is plain half-pixel bilinear — the same sampling as
// jax.image.resize(method="bilinear") so host-side and on-device resizes
// agree; note PIL's BILINEAR uses an adaptive triangle filter on
// downscale, which intentionally differs.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>
#include <setjmp.h>

namespace {

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

bool is_jpeg(const uint8_t* d, uint64_t n) {
  return n >= 3 && d[0] == 0xFF && d[1] == 0xD8 && d[2] == 0xFF;
}

bool is_png(const uint8_t* d, uint64_t n) {
  return n >= 8 && d[0] == 0x89 && d[1] == 'P' && d[2] == 'N' && d[3] == 'G';
}

// -> 0 ok, negative error codes (see sdl_decode_resize docstring python-side)
int decode_jpeg(const uint8_t* data, uint64_t len, std::vector<uint8_t>& pix,
                int32_t& h, int32_t& w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  w = static_cast<int32_t>(cinfo.output_width);
  h = static_cast<int32_t>(cinfo.output_height);
  pix.resize(static_cast<size_t>(h) * w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pix.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int decode_png_bytes(const uint8_t* data, uint64_t len,
                     std::vector<uint8_t>& pix, int32_t& h, int32_t& w) {
  png_image img;
  std::memset(&img, 0, sizeof img);
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, data, len)) return -3;
  img.format = PNG_FORMAT_RGB;
  h = static_cast<int32_t>(img.height);
  w = static_cast<int32_t>(img.width);
  pix.resize(PNG_IMAGE_SIZE(img));
  if (!png_image_finish_read(&img, nullptr, pix.data(), 0, nullptr)) {
    png_image_free(&img);
    return -4;
  }
  return 0;
}

int decode_any(const uint8_t* data, uint64_t len, std::vector<uint8_t>& pix,
               int32_t& h, int32_t& w) {
  if (is_jpeg(data, len)) return decode_jpeg(data, len, pix, h, w);
  if (is_png(data, len)) return decode_png_bytes(data, len, pix, h, w);
  return -1;  // unknown format
}

// One output coordinate's input taps for a triangle (tent) filter with
// antialiasing: on downscale the kernel stretches by the scale factor —
// the same construction as jax.image.resize(method="bilinear") and PIL's
// BILINEAR, so host-side and on-device resizes agree.
struct Taps {
  int32_t lo = 0;
  std::vector<float> w;
};

std::vector<Taps> make_taps(int32_t src_n, int32_t dst_n) {
  const float scale = static_cast<float>(src_n) / dst_n;
  const float support = std::max(scale, 1.0f);  // tent half-width in src px
  std::vector<Taps> taps(dst_n);
  for (int32_t o = 0; o < dst_n; ++o) {
    const float center = (o + 0.5f) * scale - 0.5f;
    int32_t lo = static_cast<int32_t>(std::ceil(center - support));
    int32_t hi = static_cast<int32_t>(std::floor(center + support));
    Taps& t = taps[o];
    t.lo = std::max(lo, 0);
    const int32_t hic = std::min(hi, src_n - 1);
    float sum = 0.0f;
    for (int32_t i = t.lo; i <= hic; ++i) {
      float u = std::abs((i - center) / support);
      float wgt = u < 1.0f ? 1.0f - u : 0.0f;
      t.w.push_back(wgt);
      sum += wgt;
    }
    if (sum <= 0.0f) {  // degenerate (1-px source edge): nearest
      t.lo = std::clamp(static_cast<int32_t>(std::round(center)), 0, src_n - 1);
      t.w.assign(1, 1.0f);
      sum = 1.0f;
    }
    for (float& wgt : t.w) wgt /= sum;
  }
  return taps;
}

// Separable antialiased tent resize, RGB u8 -> RGB u8 (f32 intermediate).
void resize_bilinear(const uint8_t* src, int32_t sh, int32_t sw, uint8_t* dst,
                     int32_t th, int32_t tw) {
  if (sh == th && sw == tw) {
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * 3);
    return;
  }
  const auto tx = make_taps(sw, tw);
  const auto ty = make_taps(sh, th);
  // Pass 1: horizontal, [sh, sw, 3] -> [sh, tw, 3] f32.
  std::vector<float> mid(static_cast<size_t>(sh) * tw * 3);
  for (int32_t y = 0; y < sh; ++y) {
    const uint8_t* row = src + static_cast<size_t>(y) * sw * 3;
    float* out = mid.data() + static_cast<size_t>(y) * tw * 3;
    for (int32_t x = 0; x < tw; ++x) {
      const Taps& t = tx[x];
      float acc[3] = {0, 0, 0};
      for (size_t k = 0; k < t.w.size(); ++k) {
        const uint8_t* p = row + (static_cast<size_t>(t.lo) + k) * 3;
        for (int c = 0; c < 3; ++c) acc[c] += t.w[k] * p[c];
      }
      for (int c = 0; c < 3; ++c) out[x * 3 + c] = acc[c];
    }
  }
  // Pass 2: vertical, [sh, tw, 3] -> [th, tw, 3] u8.
  for (int32_t y = 0; y < th; ++y) {
    const Taps& t = ty[y];
    uint8_t* out = dst + static_cast<size_t>(y) * tw * 3;
    for (int32_t x = 0; x < tw; ++x) {
      float acc[3] = {0, 0, 0};
      for (size_t k = 0; k < t.w.size(); ++k) {
        const float* p =
            mid.data() + ((static_cast<size_t>(t.lo) + k) * tw + x) * 3;
        for (int c = 0; c < 3; ++c) acc[c] += t.w[k] * p[c];
      }
      for (int c = 0; c < 3; ++c)
        out[x * 3 + c] =
            static_cast<uint8_t>(std::clamp(acc[c] + 0.5f, 0.0f, 255.0f));
    }
  }
}

int decode_resize_one(const uint8_t* data, uint64_t len, int32_t th,
                      int32_t tw, uint8_t* out) {
  std::vector<uint8_t> pix;
  int32_t h = 0, w = 0;
  int rc = decode_any(data, len, pix, h, w);
  if (rc != 0) return rc;
  resize_bilinear(pix.data(), h, w, out, th, tw);
  return 0;
}

}  // namespace

extern "C" {

// Header-only probe: native dimensions + source channel count (1 =
// grayscale, 3 = color, 4 = color+alpha) without a full decode.
// -> 0 ok; -1 unknown format; -2/-3 decode error.
int32_t sdl_image_info(const uint8_t* data, uint64_t len, int32_t* h,
                       int32_t* w, int32_t* channels) {
  if (is_jpeg(data, len)) {
    jpeg_decompress_struct cinfo;
    JpegErr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jpeg_err_exit;
    if (setjmp(jerr.jb)) {
      jpeg_destroy_decompress(&cinfo);
      return -2;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, len);
    jpeg_read_header(&cinfo, TRUE);
    *w = static_cast<int32_t>(cinfo.image_width);
    *h = static_cast<int32_t>(cinfo.image_height);
    *channels = static_cast<int32_t>(cinfo.num_components);
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  if (is_png(data, len)) {
    png_image img;
    std::memset(&img, 0, sizeof img);
    img.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&img, data, len)) return -3;
    *h = static_cast<int32_t>(img.height);
    *w = static_cast<int32_t>(img.width);
    *channels = static_cast<int32_t>(PNG_IMAGE_PIXEL_CHANNELS(img.format));
    png_image_free(&img);
    return 0;
  }
  return -1;
}

// Decode one image and bilinear-resize into out[th, tw, 3] RGB u8.
int32_t sdl_decode_resize(const uint8_t* data, uint64_t len, int32_t th,
                          int32_t tw, uint8_t* out) {
  return decode_resize_one(data, len, th, tw, out);
}

// Threaded batch: decode n images into out[n, th, tw, 3]; statuses[i] gets
// each image's return code (failed rows leave their slice zeroed).
void sdl_decode_resize_batch(uint64_t n, const uint8_t** datas,
                             const uint64_t* lens, int32_t th, int32_t tw,
                             uint8_t* out, int32_t n_threads,
                             int32_t* statuses) {
  const size_t frame = static_cast<size_t>(th) * tw * 3;
  std::memset(out, 0, frame * n);
  int32_t workers = std::max<int32_t>(
      1, std::min<int32_t>(n_threads, static_cast<int32_t>(n)));
  std::vector<std::thread> threads;
  std::atomic<uint64_t> next{0};
  auto work = [&] {
    for (uint64_t i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      statuses[i] =
          decode_resize_one(datas[i], lens[i], th, tw, out + frame * i);
    }
  };
  for (int32_t t = 1; t < workers; ++t) threads.emplace_back(work);
  work();
  for (auto& t : threads) t.join();
}

}  // extern "C"
