// sdl_bridge: native staging layer for the TPU infeed path.
//
// TPU-native replacement for the capability the reference gets from the
// TensorFrames JNI bridge (SURVEY.md 2.15): moving DataFrame batches from
// the host runtime into device-feedable buffers without Python-loop
// overhead. Two pieces:
//
//   1. A fixed-slot staging ring (producer/consumer, FIFO, blocking with
//      timeouts) whose slots are stable, aligned allocations — batches are
//      assembled into a slot, handed to the transfer thread, and the slot
//      is recycled only after the device copy completes. This is the
//      double-buffered infeed the BASELINE.json north-star names.
//   2. Multi-threaded row packing: scatter N variable-length rows into a
//      contiguous padded [bucket, row_stride] matrix (memcpy fan-out),
//      the hot row-assembly loop that a Python loop serializes.
//
// Concurrency design is deliberately boring - one mutex + two condvars per
// ring, state machine per slot - so it is ThreadSanitizer-clean (see
// Makefile `tsan` target).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

enum class SlotState : uint8_t { kFree, kWriting, kReady, kReading };

struct Slot {
  uint8_t* data = nullptr;
  uint64_t n_rows = 0;
  uint64_t used_bytes = 0;
  SlotState state = SlotState::kFree;
};

constexpr size_t kAlign = 64;  // cache line; also friendly to DMA engines

}  // namespace

struct SdlRing {
  uint64_t slot_bytes = 0;
  std::vector<Slot> slots;
  std::deque<uint32_t> free_q;   // FIFO of free slot indices
  std::deque<uint32_t> ready_q;  // FIFO of committed slot indices
  std::mutex mu;
  std::condition_variable cv_free;
  std::condition_variable cv_ready;
  bool closed = false;

  ~SdlRing() {
    for (auto& s : slots) ::free(s.data);
  }
};

extern "C" {

SdlRing* sdl_ring_create(uint64_t slot_bytes, uint32_t n_slots) {
  if (slot_bytes == 0 || n_slots == 0) return nullptr;
  auto* r = new (std::nothrow) SdlRing();
  if (!r) return nullptr;
  r->slot_bytes = slot_bytes;
  r->slots.resize(n_slots);
  for (uint32_t i = 0; i < n_slots; ++i) {
    void* p = nullptr;
    if (posix_memalign(&p, kAlign, slot_bytes) != 0) {
      delete r;
      return nullptr;
    }
    r->slots[i].data = static_cast<uint8_t*>(p);
    r->free_q.push_back(i);
  }
  return r;
}

void sdl_ring_destroy(SdlRing* r) { delete r; }

uint64_t sdl_ring_slot_bytes(SdlRing* r) { return r->slot_bytes; }
uint32_t sdl_ring_n_slots(SdlRing* r) {
  return static_cast<uint32_t>(r->slots.size());
}

uint8_t* sdl_ring_slot_ptr(SdlRing* r, uint32_t idx) {
  if (idx >= r->slots.size()) return nullptr;
  return r->slots[idx].data;
}

// Returns a slot index to write into, or -1 on timeout / closed ring.
int64_t sdl_ring_acquire_write(SdlRing* r, double timeout_s) {
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [r] { return !r->free_q.empty() || r->closed; };
  if (timeout_s < 0) {
    r->cv_free.wait(lk, pred);
  } else if (!r->cv_free.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (r->closed || r->free_q.empty()) return -1;
  uint32_t idx = r->free_q.front();
  r->free_q.pop_front();
  r->slots[idx].state = SlotState::kWriting;
  return idx;
}

void sdl_ring_commit_write(SdlRing* r, uint32_t idx, uint64_t n_rows,
                           uint64_t used_bytes) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    Slot& s = r->slots[idx];
    s.n_rows = n_rows;
    s.used_bytes = used_bytes;
    s.state = SlotState::kReady;
    r->ready_q.push_back(idx);
  }
  r->cv_ready.notify_one();
}

// Producer changed its mind (e.g. error while filling): return the slot.
void sdl_ring_abort_write(SdlRing* r, uint32_t idx) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->slots[idx].state = SlotState::kFree;
    r->free_q.push_back(idx);
  }
  r->cv_free.notify_one();
}

// Returns a committed slot index (FIFO), or -1 on timeout, or -2 when the
// ring is closed AND drained (end of stream).
int64_t sdl_ring_acquire_read(SdlRing* r, double timeout_s) {
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [r] { return !r->ready_q.empty() || r->closed; };
  if (timeout_s < 0) {
    r->cv_ready.wait(lk, pred);
  } else if (!r->cv_ready.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (r->ready_q.empty()) return r->closed ? -2 : -1;
  uint32_t idx = r->ready_q.front();
  r->ready_q.pop_front();
  r->slots[idx].state = SlotState::kReading;
  return idx;
}

uint64_t sdl_ring_slot_rows(SdlRing* r, uint32_t idx) {
  std::lock_guard<std::mutex> lk(r->mu);
  return r->slots[idx].n_rows;
}

uint64_t sdl_ring_slot_used(SdlRing* r, uint32_t idx) {
  std::lock_guard<std::mutex> lk(r->mu);
  return r->slots[idx].used_bytes;
}

void sdl_ring_release_read(SdlRing* r, uint32_t idx) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->slots[idx].state = SlotState::kFree;
    r->free_q.push_back(idx);
  }
  r->cv_free.notify_one();
}

// Producer signals end-of-stream; readers drain then get -2.
void sdl_ring_close(SdlRing* r) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->cv_free.notify_all();
  r->cv_ready.notify_all();
}

int sdl_ring_closed(SdlRing* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return r->closed ? 1 : 0;
}

// ---------------------------------------------------------------------------
// Multi-threaded row packing
// ---------------------------------------------------------------------------

// Scatter n_rows variable-length rows into dst with fixed row_stride.
// Bytes past each row's length up to row_stride are zero-filled. Rows
// [n_rows, pad_rows) are filled with a copy of row `pad_src_row` (the
// bucketed-padding convention: repeats of a valid row are numerically
// harmless and keep shapes static for XLA).
void sdl_pack_rows(uint8_t* dst, const uint8_t* const* srcs,
                   const uint64_t* src_bytes, uint64_t n_rows,
                   uint64_t pad_rows, uint64_t pad_src_row,
                   uint64_t row_stride, uint32_t n_threads) {
  if (n_rows == 0 && pad_rows == 0) return;
  if (n_threads == 0) n_threads = 1;
  const uint64_t total = pad_rows > n_rows ? pad_rows : n_rows;
  n_threads = static_cast<uint32_t>(
      std::min<uint64_t>(n_threads, total));

  auto work = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; ++i) {
      uint8_t* out = dst + i * row_stride;
      if (i < n_rows) {
        const uint64_t nb = src_bytes[i] < row_stride ? src_bytes[i] : row_stride;
        std::memcpy(out, srcs[i], nb);
        if (nb < row_stride) std::memset(out + nb, 0, row_stride - nb);
      } else {
        // padding row: replicate pad_src_row's packed form; with no source
        // rows at all (n_rows==0, pad-only call) pad with zeros — srcs is
        // empty, so there is nothing to replicate.
        if (n_rows == 0) {
          std::memset(out, 0, row_stride);
        } else {
          const uint64_t j = pad_src_row < n_rows ? pad_src_row : 0;
          const uint64_t nb = src_bytes[j] < row_stride ? src_bytes[j] : row_stride;
          std::memcpy(out, srcs[j], nb);
          if (nb < row_stride) std::memset(out + nb, 0, row_stride - nb);
        }
      }
    }
  };

  if (n_threads == 1) {
    work(0, total);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const uint64_t chunk = (total + n_threads - 1) / n_threads;
  for (uint32_t t = 0; t < n_threads; ++t) {
    const uint64_t lo = t * chunk;
    const uint64_t hi = std::min<uint64_t>(lo + chunk, total);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// uint8 -> float32 with affine transform (scale * x + bias), threaded.
// Host-side fallback for feeds that must arrive as float (device-side
// preprocessing is preferred; see ops/preprocess.py).
void sdl_u8_to_f32(float* dst, const uint8_t* src, uint64_t n, float scale,
                   float bias, uint32_t n_threads) {
  if (n == 0) return;
  if (n_threads == 0) n_threads = 1;
  n_threads = static_cast<uint32_t>(std::min<uint64_t>(n_threads, n));
  auto work = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; ++i)
      dst[i] = scale * static_cast<float>(src[i]) + bias;
  };
  if (n_threads == 1) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  const uint64_t chunk = (n + n_threads - 1) / n_threads;
  for (uint32_t t = 0; t < n_threads; ++t) {
    const uint64_t lo = t * chunk;
    const uint64_t hi = std::min<uint64_t>(lo + chunk, n);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
