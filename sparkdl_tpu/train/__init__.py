"""Training loops and fine-tune drivers (the workloads TPURunner launches).

Reference parity: HorovodRunner's user fn is an arbitrary training loop
(SURVEY.md 3.4); these are the framework-provided equivalents for the
BASELINE.md benchmark configs — ResNet ImageNet-style training and BERT
fine-tuning — written as pure-JAX steps that shard over the mesh's data
axes and run unchanged under one chip, a v5e slice, or the CPU test mesh.
"""

from sparkdl_tpu.train.finetune import (
    TrainState,
    classification_train_step,
    finetune_classifier,
)

__all__ = ["TrainState", "classification_train_step", "finetune_classifier"]
