"""Data-parallel classification fine-tuning (BERT-base config and friends).

The step is plain jit-over-mesh SPMD: batch sharded on the data axes,
params replicated (or tp-sharded when the model's kernels carry tp
metadata), gradient psum inserted by XLA from the shardings — the
HorovodRunner `hvd.DistributedOptimizer` allreduce (SURVEY.md 3.4) with no
user-space ring. Drop the returned ``train_fn`` into ``TPURunner.run`` for
the multi-host form.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.runtime.mesh import data_parallel_mesh, mesh_context

_M_STEPS = registry().counter(
    "sparkdl_train_steps_total", "optimizer steps taken")
_M_EXAMPLES = registry().counter(
    "sparkdl_train_examples_total", "examples consumed by training")
_M_STEP_TIME = registry().histogram(
    "sparkdl_train_step_seconds", "train step wall time (dispatch + sync)")


@flax.struct.dataclass
class TrainState:
    """Pytree train state (params/opt_state/step cross the jit boundary)."""

    params: Any
    opt_state: Any
    step: jax.Array


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def classification_train_step(
    apply_fn: Callable[..., jax.Array],
    tx: optax.GradientTransformation,
) -> Callable:
    """Jittable (state, batch) -> (state, metrics) step.

    ``apply_fn(params, **batch_inputs) -> logits``; batch is a dict with
    ``labels`` plus whatever apply_fn consumes.
    """

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        labels = batch["labels"]
        inputs = {k: v for k, v in batch.items() if k != "labels"}

        def loss_fn(params):
            logits = apply_fn(params, **inputs)
            return softmax_cross_entropy(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return (
            state.replace(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return step


def finetune_classifier(
    apply_fn: Callable[..., jax.Array],
    params: Any,
    batches: Iterator[dict] | list[dict],
    *,
    learning_rate: float = 2e-5,
    weight_decay: float = 0.01,
    tx: "optax.GradientTransformation | None" = None,
    mesh: Mesh | None = None,
    metrics_cb: Callable[[dict], None] | None = None,
    checkpoint_dir: "str | None" = None,
    checkpoint_every: int = 100,
    keep_checkpoints: int = 3,
) -> tuple[Any, list[dict]]:
    """Run the fine-tune loop over ``batches``; returns (params, history).

    Each batch dict's arrays are placed batch-sharded over the mesh's data
    axes before the jitted step — under TPURunner each process feeds its
    local shard of the global batch.

    ``tx`` overrides the default ``adamw(learning_rate, weight_decay)``
    optimizer — pass any optax chain (warmup/cosine schedules,
    ``optax.MultiSteps`` gradient accumulation, clipping, ...) without
    forking the loop.

    With ``checkpoint_dir`` set, the full train state is async-saved every
    ``checkpoint_every`` steps plus once at the end, and an existing
    checkpoint in that directory is resumed from (already-trained steps are
    skipped) — the barrier-retry resume story from SURVEY.md §5.
    """
    if mesh is None:
        mesh = data_parallel_mesh()
    if tx is None:
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    step = jax.jit(classification_train_step(apply_fn, tx))

    data_sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    repl = NamedSharding(mesh, P())
    ckpt = None
    if checkpoint_dir is not None:
        from sparkdl_tpu.checkpoint import CheckpointManager

        ckpt = CheckpointManager(
            checkpoint_dir, keep=keep_checkpoints,
            save_interval_steps=checkpoint_every,
        )
    try:
        with mesh_context(mesh):
            state = TrainState(
                params=jax.device_put(params, repl),
                opt_state=jax.device_put(tx.init(params), repl),
                # commit the scalar too: an uncommitted device-0 step next
                # to 8-device params is a mixed-device error under jit on
                # runtimes without an ambient-mesh auto-commit
                step=jax.device_put(jnp.zeros((), jnp.int32), repl),
            )
            resume_step = 0
            if ckpt is not None and ckpt.latest_step() is not None:
                state = ckpt.restore(template=state)
                resume_step = int(state.step)
            history: list[dict] = []
            last_saved = resume_step
            for i, batch in enumerate(batches):
                if i < resume_step:  # deterministic iterator replay on resume
                    continue
                n_examples = len(next(iter(batch.values())))
                with span("train.step", step=i, examples=n_examples):
                    batch = {
                        k: jax.device_put(jnp.asarray(v), data_sharding)
                        for k, v in batch.items()
                    }
                    t0 = time.perf_counter()
                    state, metrics = step(state, batch)
                    metrics = {k: float(v) for k, v in metrics.items()}
                    metrics["step_time_s"] = time.perf_counter() - t0
                metrics["step"] = int(state.step)
                _M_STEPS.inc()
                _M_EXAMPLES.inc(n_examples)
                _M_STEP_TIME.observe(metrics["step_time_s"])
                history.append(metrics)
                if metrics_cb is not None:
                    metrics_cb(metrics)
                if ckpt is not None:
                    if ckpt.save(int(state.step), state):
                        last_saved = int(state.step)
            if (
                ckpt is not None
                and int(state.step) > resume_step
                and last_saved != int(state.step)
            ):
                # final state always lands regardless of the interval policy
                ckpt.save(int(state.step), state, force=True)
            return state.params, history
    finally:
        if ckpt is not None:
            ckpt.close()


def batches_from_arrays(
    arrays: dict[str, np.ndarray], batch_size: int, *, epochs: int = 1,
    seed: int = 0, drop_remainder: bool = True,
) -> Iterator[dict]:
    """Shuffled minibatch iterator over same-length arrays (tiny-data path,
    the KerasImageFileEstimator-style in-memory fit)."""
    n = len(next(iter(arrays.values())))
    rng = np.random.default_rng(seed)
    for _ in range(epochs):
        order = rng.permutation(n)
        end = n - n % batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            idx = order[i:i + batch_size]
            yield {k: v[idx] for k, v in arrays.items()}
