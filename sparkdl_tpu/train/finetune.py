"""Data-parallel classification fine-tuning (BERT-base config and friends).

The step is plain jit-over-mesh SPMD: batch sharded on the data axes,
params replicated (or tp-sharded when the model's kernels carry tp
metadata), gradient psum inserted by XLA from the shardings — the
HorovodRunner `hvd.DistributedOptimizer` allreduce (SURVEY.md 3.4) with no
user-space ring. Drop the returned ``train_fn`` into ``TPURunner.run`` for
the multi-host form.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.partition import DataParallelPartitioner, Partitioner
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.runtime.completion import AsyncFetcher
from sparkdl_tpu.runtime.dispatch import (
    ChainPolicy,
    chain_carry,
    record_dispatch,
    shape_key,
)

_M_STEPS = registry().counter(
    "sparkdl_train_steps_total", "optimizer steps taken")
_M_EXAMPLES = registry().counter(
    "sparkdl_train_examples_total", "examples consumed by training")
_M_STEP_TIME = registry().histogram(
    "sparkdl_train_step_seconds", "train step wall time (dispatch + sync)")


@flax.struct.dataclass
class TrainState:
    """Pytree train state (params/opt_state/step cross the jit boundary)."""

    params: Any
    opt_state: Any
    step: jax.Array


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def classification_train_step(
    apply_fn: Callable[..., jax.Array],
    tx: optax.GradientTransformation,
) -> Callable:
    """Jittable (state, batch) -> (state, metrics) step.

    ``apply_fn(params, **batch_inputs) -> logits``; batch is a dict with
    ``labels`` plus whatever apply_fn consumes.
    """

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        labels = batch["labels"]
        inputs = {k: v for k, v in batch.items() if k != "labels"}

        def loss_fn(params):
            logits = apply_fn(params, **inputs)
            return softmax_cross_entropy(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return (
            state.replace(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return step


def finetune_classifier(
    apply_fn: Callable[..., jax.Array],
    params: Any,
    batches: Iterator[dict] | list[dict],
    *,
    learning_rate: float = 2e-5,
    weight_decay: float = 0.01,
    tx: "optax.GradientTransformation | None" = None,
    mesh: Mesh | None = None,
    partitioner: "Partitioner | None" = None,
    metrics_cb: Callable[[dict], None] | None = None,
    checkpoint_dir: "str | None" = None,
    checkpoint_every: int = 100,
    keep_checkpoints: int = 3,
    chain_steps: "int | None" = 1,
    input_prefetch: "int | None" = None,
    autotune: "bool | None" = None,
) -> tuple[Any, list[dict]]:
    """Run the fine-tune loop over ``batches``; returns (params, history).

    Each batch dict's arrays are placed batch-sharded over the mesh's data
    axes before the jitted step — under TPURunner each process feeds its
    local shard of the global batch.

    ``tx`` overrides the default ``adamw(learning_rate, weight_decay)``
    optimizer — pass any optax chain (warmup/cosine schedules,
    ``optax.MultiSteps`` gradient accumulation, clipping, ...) without
    forking the loop.

    ``partitioner`` owns every placement decision (partition/): batch
    sharding, param/optimizer-state layout, and the step's sharding
    constraints. Default: :class:`~sparkdl_tpu.partition.
    DataParallelPartitioner` over ``mesh`` (or all local devices) — the
    exact historical dp behavior. Pass
    ``DataParallelPartitioner(make_mesh(dp=4, fsdp=2), zero_axis="fsdp")``
    for ZeRO-sharded optimizer state (per-chip opt memory ~1/fsdp,
    measured into ``sparkdl_opt_state_bytes{axis}``), or an
    :class:`~sparkdl_tpu.partition.SPMDPartitioner` for rule-placed
    tp/fsdp params. The loss trajectory is invariant across
    partitioners up to float reduction order.

    ``chain_steps`` fuses K optimizer steps into ONE device dispatch
    (``lax.scan`` with the TrainState donated — runtime/dispatch.py),
    amortizing the per-dispatch gap that dominates short steps on relayed
    backends (PERF.md). The loss/accuracy trajectory in ``history`` stays
    per-step and numerically identical — the scan collects every step's
    metrics — but host-side work (metrics_cb, checkpoint saves, registry
    updates) happens once per K steps. None = auto-calibrate K from
    measured step time vs the dispatch gap; 1 (default) = one dispatch
    per step, the exact pre-chaining behavior.

    Host-metric reads are asynchronous (runtime/completion.py): each
    dispatch's metric values start their device→host copy immediately
    and are folded into ``history``/``metrics_cb`` one dispatch later,
    behind the next dispatch — same values, same order, no blocking
    device read on the hot path (checkpoint cadence stays at dispatch
    boundaries, driven by a host-side step counter).

    With ``checkpoint_dir`` set, the full train state is async-saved every
    ``checkpoint_every`` steps plus once at the end, and an existing
    checkpoint in that directory is resumed from (already-trained steps are
    skipped) — the barrier-retry resume story from SURVEY.md §5.

    ``input_prefetch`` is the input iterator's host-side readahead depth
    (sparkdl_tpu/ingest): a background producer keeps that many batches
    staged ahead of the dispatch loop, so a slow ``batches`` source
    (decode, augmentation, a remote read) overlaps the device step
    instead of serializing with it. The batch stream — order, values,
    resume replay — is exactly the pre-pipeline iterator's (parity
    pinned by tests/ingest/test_ported_parity.py). None = auto
    (``SPARKDL_TPU_PREFETCH`` pin, else 2; a live autotuner knob when
    ``autotune`` resolves on); 0 disables readahead (the strictly
    consumer-pulled pre-pipeline behavior); an explicit depth pins.
    """
    if chain_steps is not None and chain_steps < 1:
        raise ValueError(f"chain_steps must be >= 1, got {chain_steps}")
    if partitioner is None:
        # mesh= keeps its historical meaning: dp over that mesh's data
        # axes. Anything richer (ZeRO opt-state sharding, rule-placed
        # tp/fsdp params) is spelled as a Partitioner.
        partitioner = DataParallelPartitioner(mesh=mesh)
    elif mesh is not None and partitioner.mesh is not mesh:
        raise ValueError(
            "pass either mesh= or partitioner= (the partitioner owns "
            "its mesh), not both"
        )
    if tx is None:
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    # one tree convention inside the loop: flax Partitioned boxes are
    # sharding METADATA, and the partitioner is now the object that owns
    # placement — unbox up front so params, grads, and optimizer state
    # all flatten identically (a boxed tx.init against unboxed grads is
    # a tree-structure mismatch deep inside optax)
    from sparkdl_tpu.partition.partitioner import _unbox

    params = _unbox(params)
    step_fn = classification_train_step(apply_fn, tx)
    policy = ChainPolicy(
        max_chain=chain_steps if chain_steps is not None else 32
    )
    if chain_steps is None:
        policy.gap()  # auto mode: calibrate before the loop, not inside

    data_sharding = partitioner.batch_sharding()
    # the stacked [K, batch, ...] chain feed: K is the scanned dim,
    # batch stays sharded over the data axes exactly as the single step
    chain_sharding = partitioner.chain_batch_sharding()
    ckpt = None
    if checkpoint_dir is not None:
        from sparkdl_tpu.checkpoint import CheckpointManager

        ckpt = CheckpointManager(
            checkpoint_dir, keep=keep_checkpoints,
            save_interval_steps=checkpoint_every,
        )
    # Input pipeline (sparkdl_tpu/ingest): host-side readahead between
    # the batch source and the dispatch loop. transfer=identity — device
    # placement stays in run_single/run_chain where the shardings live.
    from sparkdl_tpu import ingest
    from sparkdl_tpu.ingest.pipeline import resolve_pin

    feed_depth, feed_pinned, _ = resolve_pin(
        input_prefetch, "SPARKDL_TPU_PREFETCH", 2, what="input_prefetch")
    input_pipe: "ingest.Pipeline | None" = None
    if feed_depth > 0:
        input_pipe = ingest.Pipeline(batches, name="finetune").prefetch(
            feed_depth, transfer=lambda b: b, pinned=feed_pinned)
        if ingest.autotune_enabled(autotune):
            input_pipe.autotune(True)
        batches = input_pipe
    try:
        with partitioner.mesh_context():
            state = TrainState(
                params=partitioner.shard_params(params),
                opt_state=partitioner.shard_opt_state(tx.init(params)),
                # commit the scalar too: an uncommitted device-0 step next
                # to 8-device params is a mixed-device error under jit on
                # runtimes without an ambient-mesh auto-commit
                step=partitioner.shard_replicated(
                    jnp.zeros((), jnp.int32)),
            )
            # the ZeRO memory win (or its absence) is a measured number:
            # sparkdl_opt_state_bytes{axis} per chip, set once at init
            partitioner.export_opt_state_bytes(state.opt_state)
            # pin the output state to the input layout from INSIDE the
            # trace — survives jit, chain_carry's scan, and donation, so
            # sharded optimizer state stays sharded across every step
            state_shardings = jax.tree_util.tree_map(
                lambda a: a.sharding, state)
            wrapped_step = partitioner.wrap_step(step_fn, state_shardings)
            step = jax.jit(wrapped_step)
            chained_step = (chain_carry(wrapped_step, donate=True)
                            if chain_steps != 1 else None)
            resume_step = 0
            if ckpt is not None and ckpt.latest_step() is not None:
                state = ckpt.restore(template=state)
                resume_step = int(state.step)
            history: list[dict] = []
            last_saved = resume_step
            #: host-tracked mirror of state.step — reading the device
            #: scalar back per dispatch would cost a relay RTT on the
            #: exact path the async pipeline is hiding
            host_step = resume_step
            # Async host-metric reads (runtime/completion.py): the D2H
            # copy of each dispatch's metrics starts as soon as the
            # dispatch lands and is COLLECTED one window later, behind
            # the following dispatch — the history/metrics_cb trajectory
            # stays per-step, in order, and numerically identical; only
            # the host-side collection point moves.
            fetcher = AsyncFetcher(window=2, path="train")
            #: (ticket, wall_s, k, base_step, n_examples) awaiting emit
            deferred: "list[tuple]" = []

            def emit(entries: "list[dict]") -> None:
                for m in entries:
                    _M_STEPS.inc()
                    _M_EXAMPLES.inc(m.pop("_examples"))
                    _M_STEP_TIME.observe(m["step_time_s"])
                    history.append(m)
                    if metrics_cb is not None:
                        metrics_cb(m)

            def collect(limit: int) -> None:
                # resolve deferred metric reads down to ``limit`` in
                # flight (submission order — the trajectory never
                # reorders)
                while len(deferred) > limit:
                    ticket, wall, k, base, n_ex = deferred.pop(0)
                    ms = ticket.result()
                    emit([
                        {
                            **{key: float(np.asarray(v).reshape(-1)[j])
                               for key, v in ms.items()},
                            "step_time_s": wall / k,
                            "step": base + j + 1,
                            "_examples": n_ex,
                        }
                        for j in range(k)
                    ])

            def maybe_checkpoint() -> None:
                # checkpoint cadence stays AT the dispatch boundary (the
                # state is current here); only metric reads are deferred
                nonlocal last_saved
                if ckpt is None:
                    return
                if ckpt.save(host_step, state):
                    last_saved = host_step
                elif host_step - last_saved >= checkpoint_every:
                    # chain boundaries (step = K, 2K, ...) may never
                    # align with the manager's step-modulo policy:
                    # force whenever a full interval has passed since
                    # the last landed save, so chaining can thin the
                    # cadence but never silently disable it
                    if ckpt.save(host_step, state, force=True):
                        last_saved = host_step

            def run_single(batch: dict) -> None:
                nonlocal state, host_step
                fault_point("dispatch")
                n_examples = len(next(iter(batch.values())))
                with span("train.step", step=host_step,
                          examples=n_examples):
                    staged = {
                        k: jax.device_put(jnp.asarray(v), data_sharding)
                        for k, v in batch.items()
                    }
                    t0 = time.perf_counter()
                    state, metrics = step(state, staged)
                    # sync on the step scalar (not the metric values):
                    # the wall stays an honest device time for the
                    # ChainPolicy while the metric payload is still in
                    # async flight
                    jax.block_until_ready(state.step)
                    wall = time.perf_counter() - t0
                record_dispatch("train", 1, wall)
                policy.record(wall, 1)
                deferred.append(
                    (fetcher.submit(metrics), wall, 1, host_step,
                     n_examples)
                )
                host_step += 1
                maybe_checkpoint()
                collect(fetcher.window - 1)

            def run_chain(group: "list[dict]") -> None:
                # K steps, ONE dispatch: stack on host, scan on device
                # with the TrainState donated; per-step metrics come back
                # stacked so the recorded trajectory stays exact.
                nonlocal state, host_step
                fault_point("dispatch")
                k = len(group)
                n_examples = len(next(iter(group[0].values())))
                with span("dispatch.chain", path="train", k=k,
                          examples=k * n_examples):
                    xs = {
                        key: jax.device_put(
                            np.stack([np.asarray(b[key]) for b in group]),
                            chain_sharding,
                        )
                        for key in group[0]
                    }
                    t0 = time.perf_counter()
                    state, ms = chained_step(state, xs)
                    jax.block_until_ready(state.step)
                    wall = time.perf_counter() - t0
                record_dispatch("train", k, wall)
                policy.record(wall, k)
                deferred.append(
                    (fetcher.submit(ms), wall, k, host_step, n_examples)
                )
                host_step += k
                maybe_checkpoint()
                collect(fetcher.window - 1)

            pending: "list[dict]" = []
            pending_key = None
            try:
                for i, batch in enumerate(batches):
                    if i < resume_step:  # deterministic replay on resume
                        continue
                    if chained_step is None:
                        run_single(batch)
                        continue
                    key = shape_key(batch)
                    if pending and key != pending_key:
                        # ragged boundary (epoch-tail batch): the scan
                        # can't stack mixed shapes — flush unchained
                        for b in pending:
                            run_single(b)
                        pending = []
                    pending.append(batch)
                    pending_key = key
                    k_target = (chain_steps if chain_steps is not None
                                else policy.chain_len())
                    if len(pending) >= k_target:
                        if len(pending) > 1:
                            run_chain(pending)
                        else:
                            run_single(pending[0])
                        pending = []
                for b in pending:  # stream tail: no one-off-K compile
                    run_single(b)
            except BaseException:
                # A crashed step must not strand the metrics of steps
                # whose dispatches already LANDED: a checkpoint may cover
                # those steps, so a resume will never re-run them — the
                # crash-time drain is what keeps the recovered history
                # (reliability/supervisor.py) bitwise-complete. Best
                # effort: if the device itself died, the drain fails too
                # and those steps are re-run from the checkpoint anyway.
                try:
                    collect(0)
                except BaseException:
                    pass
                raise
            collect(0)  # drain the async metric window: history complete
            if (
                ckpt is not None
                and host_step > resume_step
                and last_saved != host_step
            ):
                # final state always lands regardless of the interval policy
                ckpt.save(host_step, state, force=True)
            return state.params, history
    finally:
        if input_pipe is not None:
            # a crash mid-loop must not leak the readahead producer
            input_pipe.close()
        if ckpt is not None:
            ckpt.close()


def batches_from_arrays(
    arrays: dict[str, np.ndarray], batch_size: int, *, epochs: int = 1,
    seed: int = 0, drop_remainder: bool = True,
) -> Iterator[dict]:
    """Shuffled minibatch iterator over same-length arrays (tiny-data path,
    the KerasImageFileEstimator-style in-memory fit)."""
    n = len(next(iter(arrays.values())))
    rng = np.random.default_rng(seed)
    for _ in range(epochs):
        order = rng.permutation(n)
        end = n - n % batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            idx = order[i:i + batch_size]
            yield {k: v[idx] for k, v in arrays.items()}
