"""Train-step builder for the CNN zoo (models with BatchNorm state).

The zoo models return ``(features, probs)`` and carry a ``batch_stats``
collection; their supervised train step therefore differs from the
stateless-encoder step in :mod:`finetune` (mutable batch_stats threaded
through, loss from probabilities). One definition here serves the
HorovodRunner-parity workload everywhere — the training benchmark, the
distributed example, and the driver dry-run all jit this same step.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax


def _nll_from_probs(probs, y):
    """The zoo models output probabilities; one NLL definition so the
    plain and fused paths stay numerically comparable."""
    logp = jnp.log(jnp.clip(probs, 1e-8))
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def vision_loss_fn(model) -> Callable:
    """Cross-entropy loss over a zoo model's ``(features, probs)`` output;
    returns ``(loss, new_batch_stats)``."""

    def loss_fn(params, batch_stats, x, y):
        (_, probs), updates = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x, train=True, mutable=["batch_stats"],
        )
        return _nll_from_probs(probs, y), updates["batch_stats"]

    return loss_fn


def _make_step(loss_fn: Callable, tx: optax.GradientTransformation,
               donate: bool) -> Callable:
    """Shared SGD step over a ``loss_fn(params, batch_stats, x, y) ->
    (loss, new_batch_stats)`` — one definition for the plain and fused
    ResNet paths so grad/update mechanics cannot drift apart."""

    def step(params: Any, batch_stats: Any, opt_state: Any, x, y):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, batch_stats, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())


def make_resnet50_fused_train_step(
    tx: optax.GradientTransformation, *,
    num_classes: int = 1000,
    dtype=jnp.bfloat16, donate: bool = False,
) -> Callable:
    """Same contract as :func:`make_vision_train_step` for ResNet50, but
    through :func:`models.resnet_fused.resnet50_fused_apply` — the Pallas
    fused-BN-epilogue forward (PERF.md training-MFU work). Operates on the
    plain ``ResNet50`` variable tree, so params/batch_stats/checkpoints
    interchange with the unfused step. Always the classification head
    (the loss needs probabilities)."""
    from sparkdl_tpu.models.resnet_fused import resnet50_fused_apply

    def loss_fn(params, batch_stats, x, y):
        (_, probs), new_stats = resnet50_fused_apply(
            {"params": params, "batch_stats": batch_stats}, x,
            train=True, num_classes=num_classes,
            include_top=True, dtype=dtype,
        )
        return _nll_from_probs(probs, y), new_stats

    return _make_step(loss_fn, tx, donate)


def make_vision_train_step(model, tx: optax.GradientTransformation,
                           *, donate: bool = False) -> Callable:
    """Jitted ``step(params, batch_stats, opt_state, x, y) ->
    (params, batch_stats, opt_state, loss)`` for a BatchNorm CNN.

    ``donate=True`` donates the state arguments (benchmark/steady-state
    loops where the caller always rebinds them).
    """
    return _make_step(vision_loss_fn(model), tx, donate)
