"""TFImageTransformer — apply an arbitrary TF graph to the image column.

Reference parity (SURVEY.md 2.5, [U: python/sparkdl/transformers/
tf_image.py]): user supplies a graph (tf.Graph / TFInputGraph) plus its
input/output tensor names; the transformer feeds decoded images and emits
either a flat float vector (``outputMode="vector"``) or a new image struct
(``outputMode="image"``). The reference splices decode/resize TF ops onto
the graph and runs it JVM-side; here decode happens host-side (imageIO),
resize targets the graph's static spatial shape when it has one, and the
graph itself runs XLA-lowered on device.
"""

from __future__ import annotations

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.graph.builder import placeholder_specs
from sparkdl_tpu.graph.input import TFInputGraph
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import (
    cached_graph_runner,
    run_partition_with_passthrough,
)
from sparkdl_tpu.transformers.named_image import _image_to_rgb_array, _resize_host

OUTPUT_MODES = ("vector", "image")


class TFImageTransformer(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    graph = Param(None, "graph",
                  "TFInputGraph (or tf.Graph/GraphDef) to apply to images")
    inputTensor = Param(
        None, "inputTensor",
        "name of the graph's image input tensor (needed for raw graphs)",
    )
    outputTensor = Param(
        None, "outputTensor",
        "name of the graph's output tensor (needed for raw graphs)",
    )
    outputMode = Param(
        None, "outputMode", "'vector' (flat floats) or 'image' (image struct)",
        SparkDLTypeConverters.supportedNameConverter(list(OUTPUT_MODES)),
    )

    def __init__(self, inputCol=None, outputCol=None, graph=None,
                 inputTensor=None, outputTensor=None, outputMode=None,
                 batchSize=None):
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64)
        self._set(inputCol=inputCol, outputCol=outputCol, graph=graph,
                  inputTensor=inputTensor, outputTensor=outputTensor,
                  outputMode=outputMode, batchSize=batchSize)

    def getGraph(self):
        return self.getOrDefault("graph")

    def _resolved_graph(self) -> TFInputGraph:
        g = self.getGraph()
        if isinstance(g, TFInputGraph):
            return g
        from sparkdl_tpu.graph import utils as tfx
        from sparkdl_tpu.graph._tf import require_tf

        tf = require_tf()
        in_name = self.getOrDefault("inputTensor")
        out_name = self.getOrDefault("outputTensor")
        if in_name is None or out_name is None:
            raise ValueError(
                "raw graphs need inputTensor/outputTensor names; or pass a "
                "TFInputGraph"
            )
        in_name, out_name = tfx.tensor_name(in_name), tfx.tensor_name(out_name)
        if isinstance(g, tf.Graph):
            with tf.compat.v1.Session(graph=g) as sess:
                return TFInputGraph.fromGraph(g, sess, [in_name], [out_name])
        # assume GraphDef proto
        return TFInputGraph.fromGraphDef(g, [in_name], [out_name])

    def _transform(self, dataset):
        gin = self._resolved_graph()
        if len(gin.input_names) != 1 or len(gin.output_names) != 1:
            raise ValueError(
                "TFImageTransformer expects a single-input single-output "
                f"graph, got {gin.input_names} -> {gin.output_names}"
            )
        (spec,) = placeholder_specs(gin.graph_def, gin.input_names)
        shape = spec.shape.as_list() if spec.shape is not None else None
        if shape is not None and len(shape) == 4:
            batched_input, spatial = True, shape[1:3]
        elif shape is not None and len(shape) == 3:
            batched_input, spatial = False, shape[0:2]
        else:
            raise ValueError(
                f"image input tensor must be rank 3 or 4, got shape {shape}"
            )
        static_size = (
            (int(spatial[0]), int(spatial[1]))
            if all(s is not None for s in spatial)
            else None
        )
        in_dtype = spec.dtype.as_numpy_dtype
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        output_mode = self.getOrDefault("outputMode")
        batch_size = self.getBatchSize() if batched_input else 1

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            runner = self._runner(
                gin, batched_input, batch_size,
                ragged_rows=static_size is None,
            )

            def extract(row):
                arr = _image_to_rgb_array(row[input_col])
                if static_size is not None:
                    arr = _resize_host(arr, static_size)
                return {"img": np.asarray(arr, dtype=in_dtype)}

            return run_partition_with_passthrough(
                rows, extract, runner, output_col,
                self._postprocess(output_mode), input_cols=(input_col,),
            )

        schema = [(output_col,
                   "array<float>" if output_mode == "vector"
                   else "struct<origin:string,height:int,width:int,"
                        "nChannels:int,mode:int,data:binary>")]
        return transform_partitions(dataset, partition_fn, schema)

    @staticmethod
    def _runner(gin: TFInputGraph, batched_input: bool, batch_size: int,
                ragged_rows: bool = False):
        def make_apply_fn():
            fn = gin.to_jax()
            if batched_input:
                def apply_fn(batch):
                    (out,) = fn(batch["img"])
                    return out
            else:
                # rank-3 graphs: feed one image per call (leading dim stripped)
                def apply_fn(batch):
                    (out,) = fn(batch["img"][0])
                    return out[None]
            return apply_fn

        return cached_graph_runner(
            gin, (batched_input, batch_size, ragged_rows), make_apply_fn,
            batch_size, ragged_rows=ragged_rows,
        )

    @staticmethod
    def _postprocess(output_mode: str):
        if output_mode == "vector":
            return lambda o: np.asarray(o, np.float32).reshape(-1)

        def to_image(o):
            from sparkdl_tpu.image.imageIO import imageArrayToStructBGR

            arr = np.asarray(o)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            if arr.ndim != 3:
                raise ValueError(
                    f"outputMode='image' needs a (H,W,C) output, got {arr.shape}"
                )
            return imageArrayToStructBGR(arr.astype(np.float32))

        return to_image
