"""DeepImagePredictor / DeepImageFeaturizer — named pretrained-model
transformers.

Reference parity (SURVEY.md 2.1, [U: python/sparkdl/transformers/
named_image.py]): apply a named ImageNet model to an image column;
the Predictor emits class probabilities (optionally top-K decoded), the
Featurizer emits penultimate-layer features for transfer learning. The
reference routes through a frozen TF graph in the executor JVM (2.2); here
the model is a Flax module jitted on the TPU host, fed by the shared
bucketed/prefetched runner.
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.image.imageIO import imageStructToArray
from sparkdl_tpu.image.schema import UNDEFINED_MODE, is_image_struct
from sparkdl_tpu.models.registry import SUPPORTED_MODELS, get_entry
from sparkdl_tpu.ops.preprocess import PREPROCESSORS
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import (
    BatchedRunner,
    run_partition_with_passthrough,
)


def _weights_token(weights: "str | None") -> float:
    """Cache-key component so a replaced weights file is never served stale."""
    import os

    if weights is not None and os.path.isfile(weights):
        return os.path.getmtime(weights)
    return 0.0


@functools.lru_cache(maxsize=8)
def _load_named_model(model_name: str, weights: "str | None", include_top: bool,
                      weights_token: float = 0.0):
    """Per-process cache so Spark executors build each model once."""
    from sparkdl_tpu.models.registry import build_flax_model

    return build_flax_model(model_name, weights=weights, include_top=include_top)


@functools.lru_cache(maxsize=16)
def _named_model_runner(
    model_name: str, weights: "str | None", include_top: bool,
    head: str, batch_size: int, weights_token: float = 0.0,
) -> BatchedRunner:
    """Per-process runner cache: one jax.jit per (model, head, batch size).

    Partitions rebuild closures, so caching the BatchedRunner (not just the
    model) is what keeps XLA from recompiling the network per partition.
    """
    module, variables = _load_named_model(
        model_name, weights, include_top, weights_token
    )
    preprocess = PREPROCESSORS[get_entry(model_name).preprocess]

    if model_name == "InceptionV3" and head == "features":
        # Featurization fast path: branch-merged eval forward — identical
        # math (oracle-tested, models/inception_fused.py), each mixed
        # block's input read once instead of once per 1x1 head.
        from sparkdl_tpu.models.inception_fused import (
            fused_inception_v3_features,
        )

        def apply_fn(batch):
            import jax.numpy as jnp

            return fused_inception_v3_features(
                variables, preprocess(batch["img"]), dtype=jnp.float32
            )
    else:
        def apply_fn(batch):
            x = preprocess(batch["img"])
            features, probs = module.apply(variables, x, train=False)
            return features if head == "features" else probs

    return BatchedRunner(apply_fn, batch_size=batch_size)


def _resize_host(arr: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """Per-row host resize (PIL bilinear) for ragged image sizes — the
    uniform-size fast path skips this entirely."""
    from PIL import Image

    h, w = size
    if arr.shape[-1] == 1:  # grayscale -> 3-channel, whatever the size
        arr = np.repeat(arr, 3, axis=-1)
    if arr.shape[:2] == (h, w):
        return arr.astype(np.float32)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    img = Image.fromarray(arr).resize((w, h), Image.BILINEAR)
    return np.asarray(img, dtype=np.float32)


def _image_to_rgb_array(value: Any) -> np.ndarray:
    """Accept an image struct (BGR, Spark convention) or ndarray (RGB)."""
    if is_image_struct(value):
        if value["mode"] == UNDEFINED_MODE:
            raise ValueError("undefined image")
        arr = imageStructToArray(value)
        if arr.shape[-1] >= 3:  # stored BGR -> RGB
            arr = arr[..., 2::-1] if arr.shape[-1] == 3 else np.concatenate(
                [arr[..., 2::-1], arr[..., 3:]], axis=-1
            )
        return np.asarray(arr[..., :3])
    arr = np.asarray(value)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr[..., :3]


class _NamedImageTransformer(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    """Shared engine for the named-model transformers."""

    modelName = Param(
        None, "modelName", "name of the pretrained model",
        SparkDLTypeConverters.supportedNameConverter(list(SUPPORTED_MODELS)),
    )
    weights = Param(
        None, "weights",
        "'imagenet', a local Keras .h5/.keras file, or 'random' for "
        "random init (None in the constructor means unset -> default)",
    )

    _include_top: bool = True

    def __init__(self, inputCol=None, outputCol=None, modelName=None,
                 batchSize=None, weights=None):
        super().__init__()
        self._setDefault(batchSize=64, weights="imagenet")
        self._set(inputCol=inputCol, outputCol=outputCol, modelName=modelName,
                  batchSize=batchSize, weights=weights)

    def setModelName(self, value: str):
        return self._set(modelName=value)

    def getModelName(self) -> str:
        return self.getOrDefault("modelName")

    #: which head of (features, probs) the subclass emits
    _head: str = "probs"

    def _postprocess(self, out: np.ndarray):
        return out

    def _output_schema(self) -> list[tuple[str, str]]:
        return [(self.getOutputCol(), "array<float>")]

    def _transform(self, dataset):
        model_name = self.getModelName()
        weights = self.getOrDefault("weights")
        batch_size = self.getBatchSize()
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        include_top = self._include_top
        head = self._head
        postprocess = self._postprocess

        size = get_entry(model_name).input_size

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            runner = _named_model_runner(
                model_name, weights, include_top, head, batch_size,
                _weights_token(weights),
            )

            def extract(row):
                arr = _image_to_rgb_array(row[input_col])
                return {"img": _resize_host(arr, size)}

            return run_partition_with_passthrough(
                rows, extract, runner, output_col, postprocess,
                input_cols=(input_col,),
            )

        return transform_partitions(dataset, partition_fn, self._output_schema())


class DeepImageFeaturizer(_NamedImageTransformer):
    """Transfer-learning featurizer: penultimate-layer activations.

    Reference: [U: python/sparkdl/transformers/named_image.py]
    DeepImageFeaturizer (py wrapper of the Scala core, SURVEY.md 2.1/2.2).
    """

    _include_top = False
    _head = "features"

    def _postprocess(self, out):
        return np.asarray(out, dtype=np.float32)


class DeepImagePredictor(_NamedImageTransformer):
    """Class-probability predictor with optional top-K decoding."""

    decodePredictions = Param(
        None, "decodePredictions",
        "emit top-K (class, description, probability) instead of raw probabilities",
        SparkDLTypeConverters.toBoolean,
    )
    topK = Param(None, "topK", "K for decodePredictions",
                 SparkDLTypeConverters.toInt)

    _include_top = True

    def __init__(self, inputCol=None, outputCol=None, modelName=None,
                 batchSize=None, weights=None, decodePredictions=None,
                 topK=None):
        super().__init__(inputCol, outputCol, modelName, batchSize, weights)
        self._setDefault(decodePredictions=False, topK=5)
        self._set(decodePredictions=decodePredictions, topK=topK)

    def _postprocess(self, out):
        probs = np.asarray(out, dtype=np.float32)
        if not self.getOrDefault("decodePredictions"):
            return probs
        k = self.getOrDefault("topK")
        top = np.argsort(probs)[::-1][:k]
        return [(int(i), _class_description(int(i)), float(probs[i])) for i in top]

    def _output_schema(self):
        if self.getOrDefault("decodePredictions"):
            return [(self.getOutputCol(),
                     "array<struct<class:int,description:string,probability:float>>")]
        return [(self.getOutputCol(), "array<float>")]


@functools.lru_cache(maxsize=1)
def _imagenet_class_index() -> "dict[int, tuple[str, str]] | None":
    """ImageNet class index if cached locally (zero-egress: no download)."""
    import json
    import os

    path = os.path.join(
        os.path.expanduser("~"), ".keras", "models", "imagenet_class_index.json"
    )
    if not os.path.exists(path):
        return None
    with open(path) as f:
        raw = json.load(f)
    return {int(k): (v[0], v[1]) for k, v in raw.items()}


def _class_description(idx: int) -> str:
    index = _imagenet_class_index()
    if index and idx in index:
        return index[idx][1]
    return f"class_{idx}"
