"""DeepTextFeaturizer — BERT-backed text featurization over DataFrames.

Text-side sibling of ``DeepImageFeaturizer`` (the reference has no text
models at all — its zoo is ImageNet CNNs, SURVEY.md 2.1 — but its BERT
benchmark config and the transformer surface invite exactly this class):
a column of token-id arrays goes in, pooled encoder features come out as a
float array column ready for a downstream classifier — the same
transfer-learning shape as image featurization.

Rows are padded/truncated to ``maxLength``, bucketed by batch (one XLA
compile per bucket, shared per process) and featurized by a jitted BERT
forward. Tokenization is upstream of this transformer (the reference's
imageLoader pattern: bring your own loader); pair with any tokenizer that
yields int ids, e.g. ``transformers.AutoTokenizer``.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import (
    BatchedRunner,
    run_partition_with_passthrough,
)

_POOLINGS = ("cls", "mean", "pooler")

class _LruCache(OrderedDict):
    """Tiny bounded LRU so long-lived executors hosting many models don't
    accumulate jitted programs / weight digests for the process lifetime."""

    def __init__(self, maxsize: int):
        super().__init__()
        self.maxsize = maxsize

    def get(self, key, default=None):
        if key in self:
            self.move_to_end(key)
            return self[key]
        return default

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self.maxsize:
            self.popitem(last=False)


#: per-process runner cache: one jitted BERT forward per (weights, config,
#: pooling, shapes) no matter how many partitions/tasks deserialize the
#: transformer (the sibling transformers key by model *file path*; here the
#: model arrives as live arrays, so the stable cross-deserialization key is
#: a content fingerprint). LRU-bounded: evicting a live runner only costs a
#: re-jit on next use.
_RUNNER_CACHE: _LruCache = _LruCache(maxsize=8)
#: (id(variables), cheap probe) -> full digest. The probe (leaf count +
#: total bytes + first-leaf prefix) guards against id() reuse after the
#: original pytree is garbage-collected — a bare id key could hand a new
#: model another model's fingerprint.
_FINGERPRINTS: _LruCache = _LruCache(maxsize=64)


def _fingerprint(variables) -> str:
    import jax

    leaves = sorted(
        jax.tree_util.tree_flatten_with_path(variables)[0],
        key=lambda kv: str(kv[0]),
    )
    # Probe without device->host copies: nbytes is metadata, and the first
    # leaf is sliced on-device before the 16-element transfer.
    first = (
        np.asarray(leaves[0][1].reshape(-1)[:16]).tobytes() if leaves else b""
    )
    total = sum(l.nbytes for _, l in leaves)
    key = (id(variables), len(leaves), total, first)
    fp = _FINGERPRINTS.get(key)
    if fp is None:
        h = hashlib.blake2b(digest_size=16)
        for path, leaf in leaves:
            h.update(str(path).encode())
            h.update(np.asarray(leaf).tobytes())
        fp = h.hexdigest()
        _FINGERPRINTS[key] = fp
    return fp


def _to_bundle(value):
    """Validate the model param: (BertConfig, variables) pair."""
    from sparkdl_tpu.models.bert import BertConfig

    if (
        isinstance(value, tuple)
        and len(value) == 2
        and isinstance(value[0], BertConfig)
    ):
        return value
    raise TypeError(
        "model must be a (BertConfig, variables) tuple, e.g. from "
        "models.bert.load_hf_bert(...) or (cfg, BertModel(cfg).init(...))"
    )


class DeepTextFeaturizer(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    model = Param(None, "model", "(BertConfig, variables) encoder bundle",
                  _to_bundle)
    pooling = Param(
        None, "pooling",
        "how to pool token features: 'cls' (first token), 'mean' "
        "(mask-weighted mean), 'pooler' (HF tanh pooler head)",
        SparkDLTypeConverters.toString,
    )
    maxLength = Param(None, "maxLength",
                      "pad/truncate token ids to this length",
                      SparkDLTypeConverters.toInt)

    def __init__(self, inputCol=None, outputCol=None, model=None,
                 pooling=None, maxLength=None, batchSize=None):
        super().__init__()
        self._setDefault(pooling="mean", maxLength=128, batchSize=64)
        self._set(inputCol=inputCol, outputCol=outputCol, model=model,
                  pooling=pooling, maxLength=maxLength, batchSize=batchSize)

    def setModel(self, value):
        return self._set(model=value)

    def _transform(self, dataset):
        import jax.numpy as jnp

        from sparkdl_tpu.models.bert import BertModel

        cfg, variables = self.getOrDefault("model")
        pooling = self.getOrDefault("pooling")
        if pooling not in _POOLINGS:
            raise ValueError(f"pooling must be one of {_POOLINGS}, "
                             f"got {pooling!r}")
        max_len = self.getOrDefault("maxLength")
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        module = BertModel(cfg, add_pooler=pooling == "pooler")

        batch_size = self.getBatchSize()

        def make_runner():
            def apply_fn(batch):
                ids = batch["input_ids"].astype(jnp.int32)
                mask = batch["attention_mask"].astype(jnp.int32)
                seq, pooled = module.apply(variables, ids, mask)
                if pooling == "pooler":
                    out = pooled
                elif pooling == "cls":
                    out = seq[:, 0]
                else:  # mask-weighted mean over real tokens
                    m = mask[:, :, None].astype(seq.dtype)
                    out = jnp.sum(seq * m, axis=1) / jnp.clip(
                        jnp.sum(m, axis=1), 1
                    )
                return out.astype(jnp.float32)

            return BatchedRunner(apply_fn, batch_size=batch_size)

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            key = (_fingerprint(variables), cfg, pooling, max_len, batch_size)
            runner = _RUNNER_CACHE.get(key)
            if runner is None:
                runner = _RUNNER_CACHE[key] = make_runner()

            def extract(row):
                ids = np.asarray(row[input_col], dtype=np.int32)
                if ids.ndim != 1:
                    raise ValueError(
                        f"token-id input must be 1-D, got {ids.shape}"
                    )
                n = min(len(ids), max_len)
                padded = np.zeros(max_len, np.int32)
                padded[:n] = ids[:n]
                mask = np.zeros(max_len, np.int32)
                mask[:n] = 1
                return {"input_ids": padded, "attention_mask": mask}

            return run_partition_with_passthrough(
                rows, extract, runner, output_col,
                lambda o: np.asarray(o, dtype=np.float32),
                input_cols=(input_col,),
            )

        return transform_partitions(
            dataset, partition_fn, [(output_col, "array<float>")]
        )
