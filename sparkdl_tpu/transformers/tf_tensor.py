"""TFTransformer — run an ingested TF graph over numeric DataFrame columns.

Reference parity (SURVEY.md 2.6, [U: python/sparkdl/transformers/
tf_tensor.py]): takes a ``TFInputGraph`` plus explicit input/output
tensor↔column mappings, and applies the graph per partition block. The
reference strips/optimizes the graph and ships it to the executor JVM's TF
session; here the frozen graph is XLA-lowered once (TFInputGraph.to_jax) and
driven by the shared bucketed/prefetched runner, so it fuses and runs on TPU
like native JAX code.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.graph.builder import placeholder_specs
from sparkdl_tpu.graph.input import TFInputGraph
from sparkdl_tpu.param import (
    HasBatchSize,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import cached_graph_runner


def _graph_runner(gin: TFInputGraph, batch_size: int):
    def make_apply_fn():
        fn = gin.to_jax()
        names = list(gin.input_names)
        return lambda batch: fn(*(batch[n] for n in names))

    return cached_graph_runner(gin, batch_size, make_apply_fn, batch_size)


class TFTransformer(Transformer, HasBatchSize):
    tfInputGraph = Param(
        None, "tfInputGraph", "ingested TF graph (TFInputGraph)",
        SparkDLTypeConverters.toTFInputGraph,
    )
    inputMapping = Param(
        None, "inputMapping",
        "dict: input column -> graph input (tensor name or signature key)",
        SparkDLTypeConverters.toColumnToTensorNameMap,
    )
    outputMapping = Param(
        None, "outputMapping",
        "dict: graph output (tensor name or signature key) -> output column",
        SparkDLTypeConverters.toTensorNameToColumnMap,
    )

    def __init__(self, tfInputGraph=None, inputMapping=None, outputMapping=None,
                 batchSize=None):
        super().__init__()
        self._setDefault(batchSize=256)
        self._set(tfInputGraph=tfInputGraph, inputMapping=inputMapping,
                  outputMapping=outputMapping, batchSize=batchSize)

    def getTFInputGraph(self) -> TFInputGraph:
        return self.getOrDefault("tfInputGraph")

    def getInputMapping(self) -> dict:
        return self.getOrDefault("inputMapping")

    def getOutputMapping(self) -> dict:
        return self.getOrDefault("outputMapping")

    def _transform(self, dataset):
        gin = self.getTFInputGraph()
        batch_size = self.getBatchSize()

        # column -> canonical input tensor name (signature keys resolved)
        col_to_tensor = gin.translateInputMapping(self.getInputMapping())
        # canonical output tensor name -> column
        tensor_to_col = gin.translateOutputMapping(self.getOutputMapping())

        tensor_to_colin = {t: c for c, t in col_to_tensor.items()}
        missing = [t for t in gin.input_names if t not in tensor_to_colin]
        if missing:
            raise ValueError(
                f"inputMapping covers no column for graph inputs {missing}; "
                f"graph inputs are {gin.input_names}"
            )
        # ordered column feed matching gin.input_names / to_jax arg order
        feed_cols = [tensor_to_colin[t] for t in gin.input_names]

        out_indices, out_cols = [], []
        for t, col in tensor_to_col.items():
            if t not in gin.output_names:
                raise ValueError(
                    f"outputMapping names {t!r}, not a graph output "
                    f"{gin.output_names}"
                )
            out_indices.append(gin.output_names.index(t))
            out_cols.append(col)

        in_dtypes = [
            s.dtype.as_numpy_dtype
            for s in placeholder_specs(gin.graph_def, gin.input_names)
        ]

        def partition_fn(rows) -> Iterator[dict]:
            rows = list(rows)
            if not rows:
                return iter(())
            runner = _graph_runner(gin, batch_size)

            def feeds():
                for r in rows:
                    yield {
                        t: np.asarray(r[c], dtype=dt)
                        for t, c, dt in zip(gin.input_names, feed_cols, in_dtypes)
                    }

            def emit():
                outputs = runner.run(feeds())
                for r, out in zip(rows, outputs):
                    new = dict(r)
                    for idx, col in zip(out_indices, out_cols):
                        new[col] = np.asarray(out[idx], dtype=np.float32)
                    yield new

            return emit()

        schema = [(c, "array<float>") for c in out_cols]
        return transform_partitions(dataset, partition_fn, schema)
