"""Shared batched-inference engine for all transformers.

The TPU-native replacement for the reference's per-partition
``Session.run`` hot loop (SURVEY.md 3.1/3.2): a jitted apply function mapped
over bucketed, padded batches with double-buffered host→device prefetch.
jit's shape-keyed cache means each bucket size compiles exactly once.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Sequence

import jax
import numpy as np

from sparkdl_tpu.runtime.batching import default_buckets, rebatch
from sparkdl_tpu.runtime.prefetch import prefetch_to_device


@dataclasses.dataclass
class BatchedRunner:
    """Maps ``apply_fn(batch_dict) -> output array(s)`` over row streams.

    apply_fn must be shape-polymorphic only across the bucket set (it is
    jitted; one compile per bucket). Outputs follow the batch leading dim.
    """

    apply_fn: Callable[[dict[str, Any]], Any]
    batch_size: int = 64
    prefetch: int = 2

    def __post_init__(self):
        self._jitted = jax.jit(self.apply_fn)
        self._buckets = default_buckets(self.batch_size)

    def run(self, rows: Iterator[dict[str, np.ndarray]]) -> Iterator[np.ndarray]:
        """Yield one output array per input row, in order."""
        batches = rebatch(rows, self.batch_size, self._buckets)
        # keep (n_valid) alongside the device computation
        metas: list[int] = []

        def device_batches():
            for b in batches:
                metas.append(b.n_valid)
                yield b.arrays

        results = prefetch_to_device(
            device_batches(), size=self.prefetch, transfer=self._transfer
        )
        for i, out in enumerate(map(self._jitted, results)):
            out = np.asarray(out)
            yield from out[: metas[i]]

    def _transfer(self, arrays: dict[str, np.ndarray]):
        return jax.device_put(arrays)


def run_partition_with_passthrough(
    rows: "list[dict]",
    extract: Callable[[dict], dict[str, np.ndarray]],
    runner: BatchedRunner,
    output_col: str,
    postprocess: Callable[[np.ndarray], Any] | None = None,
) -> Iterator[dict]:
    """Run inference for a partition, appending ``output_col`` to each row.

    ``extract`` turns a row into the numeric feature dict the model eats;
    rows it raises on are yielded unchanged with output None (mirrors the
    reference's tolerance of undecodable rows).
    """
    feeds: list[dict[str, np.ndarray] | None] = []
    for r in rows:
        try:
            feeds.append(extract(r))
        except Exception:
            feeds.append(None)
    valid = [f for f in feeds if f is not None]
    outputs = runner.run(iter(valid)) if valid else iter(())
    for r, f in zip(rows, feeds):
        out_row = dict(r)
        if f is None:
            out_row[output_col] = None
        else:
            o = next(outputs)
            out_row[output_col] = postprocess(o) if postprocess else o
        yield out_row


def uniform_shape(arrays: Sequence[np.ndarray]) -> "tuple | None":
    """The common shape of a list of arrays, or None if ragged."""
    if not arrays:
        return None
    s = arrays[0].shape
    return s if all(a.shape == s for a in arrays[1:]) else None
