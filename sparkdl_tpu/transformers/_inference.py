"""Shared batched-inference engine for all transformers.

The TPU-native replacement for the reference's per-partition
``Session.run`` hot loop (SURVEY.md 3.1/3.2): a jitted apply function mapped
over bucketed, padded batches with double-buffered host→device prefetch.
jit's shape-keyed cache means each bucket size compiles exactly once.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import weakref
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable, Iterator

import jax
import numpy as np

from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.runtime.batching import (
    default_buckets,
    pad_to_bucket,
)
from sparkdl_tpu.runtime.completion import (
    AsyncFetcher,
    FetchTicket,
    start_fetch,
)
from sparkdl_tpu.runtime.dispatch import (
    ChainPolicy,
    ScanChainer,
    record_dispatch,
)


@dataclasses.dataclass
class BatchedRunner:
    """Maps ``apply_fn(batch_dict) -> output array(s)`` over row streams.

    apply_fn must be shape-polymorphic only across the bucket set (it is
    jitted; one compile per bucket). Outputs follow the batch leading dim.

    Host->device staging: every uniform-row feed rides the native C++
    staging ring (:class:`~sparkdl_tpu.native.bridge.DeviceFeeder`):
    packer thread -> stable slot -> transfer thread -> device,
    double-buffered so the chip computes batch i while batch i+1 is on
    the wire and i+2 is packing — the TensorFrames-block-feed equivalent
    (SURVEY.md 2.15) on the actual hot path. Multi-tensor feeds (text's
    input_ids+attention_mask, multi-input graphs) pack as a
    struct-of-tensors slot with a fixed byte segment per key. Ragged
    feeds and hosts without the .so use the pure-Python prefetcher with
    the same overlap semantics.

    ``ragged_rows=True`` declares that row shapes vary across batches
    (e.g. un-resized images into a dynamic-spatial graph): ring slots are
    fixed-size, so such feeds must keep to the Python path.

    Local multi-chip data parallelism (SURVEY.md 2.11a: the reference
    scales inference DP over DataFrame partitions ACROSS hosts; chips
    WITHIN a host are this class's job): with ``data_parallel`` left at
    auto and >1 local device, batches land sharded over a 1-axis ``dp``
    mesh of the local devices (``jax.device_put`` with a
    ``NamedSharding`` in the transfer hook), and jit compiles the apply
    SPMD from the committed input sharding — a 4-chip host featurizes 4x
    without any Spark-side change. Bucket sizes are rounded up to
    multiples of the device count so the batch dim always divides the
    mesh; single-device hosts keep the exact single-chip behavior.
    """

    apply_fn: Callable[[dict[str, Any]], Any]
    batch_size: int = 64
    #: Staging-pipeline depth (batches in flight ahead of the device).
    #: None = auto: ``SPARKDL_TPU_PREFETCH`` env pin if set, else 2 —
    #: and the depth is a live autotuner knob when :attr:`autotune` is
    #: on. An explicit int (or the env var) PINS the depth and excludes
    #: it from tuning; both set and disagreeing fails loud.
    prefetch: "int | None" = None
    ragged_rows: bool = False
    #: None = auto (shard over local devices when there is more than one);
    #: False forces single-device; True demands >1 local device.
    data_parallel: "bool | None" = None
    #: Fused multi-step dispatch (runtime/dispatch.py): chain this many
    #: same-bucket batches per device dispatch in :meth:`run`. None =
    #: auto (``SPARKDL_TPU_CHAIN_K`` env, else the ChainPolicy picks K
    #: from measured program time vs the calibrated dispatch gap); 1
    #: disables chaining. Outputs are bitwise-identical either way —
    #: chaining is a dispatch decision, never a numeric one. Memory:
    #: chaining holds up to K staged batches (auto caps K at 8) plus a
    #: stacked [K, ...] copy inside the fused program — workloads whose
    #: per-batch inputs already sit near the HBM limit should pass
    #: ``chain_k=1`` (the chain buys nothing there anyway: big batches
    #: mean long programs, where the policy degrades to K=1 itself).
    chain_k: "int | None" = None
    #: Async completion (runtime/completion.py): start each result's
    #: device->host copy as soon as its dispatch lands and collect it
    #: while the NEXT dispatch runs, instead of the blocking
    #: ``np.asarray`` that serialized readback with dispatch. True
    #: (default) pipelines :meth:`run` readback ``fetch_window`` deep;
    #: False restores the strictly blocking readback (the parity
    #: reference — outputs are bitwise identical either way).
    async_fetch: bool = True
    #: Results in flight for the async readback window. None = auto:
    #: prefetch depth x resolved chain length (the same pipeline depth
    #: the input side already runs at), so device memory holds at most
    #: that many result buffers.
    fetch_window: "int | None" = None
    #: Pin every dispatch of this runner to ONE device (a ReplicaPool
    #: executor). Implies no local data-parallel sharding — the pool
    #: scales across devices by replication, not by splitting batches.
    #: Sugar for ``partitioner=SingleDevicePartitioner(device)``.
    device: Any = None
    #: The placement owner (sparkdl_tpu/partition): every staged batch
    #: goes through ``partitioner.shard_batch``. None = auto —
    #: :class:`~sparkdl_tpu.partition.SingleDevicePartitioner` (pinned
    #: or default device), or a
    #: :class:`~sparkdl_tpu.partition.DataParallelPartitioner` over the
    #: local devices when ``data_parallel`` resolves on. Pass one
    #: explicitly to run this runner over a custom data-parallel mesh
    #: layout (the chunk/bucket sizes round to its data-axis size).
    #: Model-axis (tp/fsdp-on-params) layouts are rejected on jax 0.4.x:
    #: this runner's bare-jit compile relies on implicit GSPMD
    #: propagation, which 0.4.x miscompiles for such params (PARITY.md)
    #: — inference through sharded params goes via
    #: ``Partitioner.wrap_apply``'s explicit shardings instead.
    partitioner: Any = None
    #: Online autotuning of the ingest knobs (sparkdl_tpu/ingest): the
    #: staging depth, the dispatch chain K, and the native packer
    #: parallelism become live knobs on the process
    #: :func:`~sparkdl_tpu.ingest.default_tuner`, resized from the
    #: measured starvation / producer-blocked shares. None = defer to
    #: ``SPARKDL_TPU_AUTOTUNE`` (default off). Explicitly pinned knobs
    #: (``prefetch=``, ``chain_k=``, their env pins) are registered for
    #: visibility but never moved.
    autotune: "bool | None" = None

    def __post_init__(self):
        from sparkdl_tpu.ingest.pipeline import resolve_pin, unique_name

        self._prefetch_depth, self._prefetch_pinned, _ = resolve_pin(
            self.prefetch, "SPARKDL_TPU_PREFETCH", 2, what="prefetch")
        self._prefetch_depth = max(1, self._prefetch_depth)
        # knob prefix: unique per RUNNER so concurrent autotuned runners
        # never collide in the tuner's name-keyed registry, while one
        # runner's successive streams (warmup, then the real run) keep
        # one stable set of names (identity-checked unregistration
        # handles the rare same-runner-concurrent-streams case)
        self._pipe_name = unique_name("batch")
        self._chainer = ScanChainer(
            self.apply_fn, path="batch", chain_k=self.chain_k,
            # auto mode holds K staged batches for the chain on top of
            # the prefetch queue: cap auto-K at 8 so peak input memory
            # stays bounded on unchanged caller code (PERF.md: K=8
            # captures most of the measured dispatch win; an explicit
            # chain_k raises the ceiling deliberately)
            policy=ChainPolicy(max_chain=8),
        )
        # run_batch and the unchained run path share this executable
        self._jitted = self._chainer.jit_single
        self._chunk = self.batch_size
        self._buckets = default_buckets(self.batch_size)
        if self.fetch_window is not None and self.fetch_window < 1:
            raise ValueError(
                f"fetch_window must be >= 1, got {self.fetch_window}"
            )
        # Placement routes through ONE object (sparkdl_tpu/partition):
        # the partitioner decides where every staged batch lands, and
        # the chunk/bucket geometry follows its data-axis size.
        from sparkdl_tpu.partition import (
            DataParallelPartitioner,
            SingleDevicePartitioner,
        )

        if self.device is not None:
            if self.data_parallel is True:
                raise ValueError(
                    "device= pins this runner to one chip; data_parallel "
                    "scaling is the ReplicaPool's job (one runner per "
                    "device), not this runner's"
                )
            if self.partitioner is not None:
                raise ValueError(
                    "device= is sugar for partitioner="
                    "SingleDevicePartitioner(device); pass one or the "
                    "other, not both"
                )
            self._partitioner = SingleDevicePartitioner(self.device)
            return
        if self.partitioner is not None:
            if self.data_parallel is True:
                raise ValueError(
                    "partitioner= owns placement; an explicit "
                    "data_parallel=True would be silently overridden — "
                    "leave it at None and encode dp in the partitioner's "
                    "mesh instead"
                )
            mesh = getattr(self.partitioner, "mesh", None)
            model_ways = (
                mesh.devices.size // self.partitioner.data_axis_size
                if mesh is not None else 1
            )
            if model_ways > 1 and not hasattr(jax, "set_mesh"):
                # this runner compiles apply_fn with a bare jit (params
                # are closure constants), i.e. implicit GSPMD
                # propagation — the form measured to miscompile
                # tp/model-axis-sharded params on jax 0.4.x (PARITY.md).
                # Refuse loudly rather than serve silently wrong logits;
                # per-replica SPMD serving sub-meshes are a ROADMAP
                # follow-on that will route through wrap_apply's
                # explicit shardings.
                raise ValueError(
                    f"partitioner shards {model_ways}-way over model "
                    "(non-batch) mesh axes, which this jax 0.4.x "
                    "runner's implicit-propagation jit miscompiles "
                    "(PARITY.md) — use a data-parallel layout here, or "
                    "Partitioner.wrap_apply for explicit-sharding "
                    "inference"
                )
            self._partitioner = self.partitioner
            self._round_to_data_axes(self._partitioner.data_axis_size)
            return
        n_local = jax.local_device_count()
        if self.data_parallel is True and n_local == 1:
            raise ValueError(
                "data_parallel=True but only one local device; use "
                "data_parallel=None for auto fallback"
            )
        self._partitioner = SingleDevicePartitioner()
        if self.data_parallel is not False and n_local > 1:
            from sparkdl_tpu.runtime.mesh import data_parallel_mesh

            # never spread a batch thinner than one row per device
            n_use = max(1, min(n_local, self.batch_size))
            if n_use == 1:
                if self.data_parallel is True:
                    raise ValueError(
                        "data_parallel=True but batch_size=1 leaves "
                        "nothing to shard"
                    )
            else:
                self._partitioner = DataParallelPartitioner(
                    data_parallel_mesh(jax.local_devices()[:n_use])
                )
                self._round_to_data_axes(n_use)

    def _round_to_data_axes(self, n_use: int) -> None:
        """Round the dispatch chunk DOWN and the buckets UP to multiples
        of the partitioner's data-axis size, so the batch dim always
        divides the mesh (never above the caller's memory ask — the
        caller-supplied ``batch_size`` field stays untouched; the
        rounded value is the private dispatch chunk)."""
        if n_use <= 1:
            return
        if self.batch_size < n_use:
            # only reachable with an explicit partitioner= (the auto-dp
            # path clamps its device count to batch_size); rounding UP
            # would dispatch more rows than the caller's memory ask
            raise ValueError(
                f"batch_size={self.batch_size} is smaller than the "
                f"partitioner's {n_use}-way data axes — every dispatch "
                f"needs at least one row per data-axis device; raise "
                f"batch_size or use a smaller mesh"
            )
        self._chunk = self.batch_size // n_use * n_use
        if self._chunk != self.batch_size:
            logging.getLogger(__name__).debug(
                "batch_size %d rounded to %d-way data-axis chunk %d "
                "(configured value preserved on .batch_size)",
                self.batch_size, n_use, self._chunk,
            )
        self._buckets = tuple(sorted({
            -(-b // n_use) * n_use
            for b in default_buckets(self._chunk)
        }))

    @property
    def _sharding(self):
        """Introspection shim: the batch ``NamedSharding`` when this
        runner splits batches over a mesh, else None. Derived from the
        partitioner — placement has exactly one owner."""
        if getattr(self._partitioner, "mesh", None) is None:
            return None
        return self._partitioner.batch_sharding()

    @property
    def chunk_size(self) -> int:
        """Rows per device dispatch: ``batch_size`` rounded down to a
        multiple of the dp device count (equal to ``batch_size`` on
        single-device hosts)."""
        return self._chunk

    @property
    def max_inflight_batches(self) -> int:
        """How many ``run_batch_async`` dispatches a caller (the
        micro-batcher) should keep in flight against this runner: one
        resolving while one runs. A :class:`~sparkdl_tpu.serving.replicas.
        ReplicaPool` overrides this with its healthy replica count."""
        return 2 if self.async_fetch else 1

    def _fetch_window(self) -> int:
        """Async readback window: prefetch depth x resolved chain length
        (a K-chain hands back K results per dispatch, so the window must
        cover ``prefetch`` dispatches' worth of outputs to keep the
        pipeline full). This holds up to that many RESULT buffers on the
        device — workloads with outputs as large as their inputs should
        pin ``fetch_window`` lower."""
        if self.fetch_window is not None:
            return self.fetch_window
        chain = self._chainer.chain_k or self._chainer.policy.max_chain
        return max(2, self._prefetch_depth) * max(1, chain)

    def run(self, rows: Iterator[dict[str, np.ndarray]]) -> Iterator[np.ndarray]:
        """Yield one output per input row, in order.

        Single-array apply_fns yield arrays; tuple-valued apply_fns (e.g.
        multi-output ingested graphs) yield per-row tuples.

        The feed is one composable ingest pipeline (sparkdl_tpu/ingest):
        ``rows -> batch(bucketing) -> to_device(ring | prefetch)`` — the
        stage chain replaces the hand-wired rebatch/_device_feed pair
        and, with :attr:`autotune` on, exports its depth plus this
        runner's chain-K and the native packer parallelism as live
        tuner knobs. Outputs are bitwise-identical to the pre-pipeline
        path (parity pinned by tests/ingest/test_ported_parity.py).
        """
        from sparkdl_tpu import ingest

        # keep (n_valid) alongside the device computation
        metas: list[int] = []
        tuning = ingest.autotune_enabled(self.autotune)
        pname = self._pipe_name
        pipe = (
            ingest.Pipeline(rows, name=pname)
            .batch(self._chunk, self._buckets)
            .tap(lambda b: metas.append(b.n_valid))
            .apply(lambda b: b.arrays)
            .to_device(
                transfer=self._transfer,
                depth=self._feed_depth(),
                ragged=self.ragged_rows,
                max_bucket=max(self._buckets),
                pinned=self._prefetch_pinned,
                # the staging depth may never shrink below the chain
                # ceiling: a K-chain consumes K staged batches per
                # dispatch, so depth < K turns chain assembly into the
                # serialization point (_feed_depth's invariant, kept
                # under tuning by the knob floor)
                lo=self._chain_floor(),
            )
        )
        if tuning:
            pipe.autotune(True, extra_knobs=self._tuning_knobs(pname))
        results = iter(pipe)
        # Fused dispatch: runs of same-bucket staged batches are chained
        # K-per-dispatch (lax.scan inside one jit) behind the prefetch
        # buffer; ragged tail buckets flush unchained. Output order and
        # values are identical to the one-dispatch-per-batch loop.
        # NOTE: the device step now lands in the chainer's
        # ``dispatch.chain`` span (path="batch"); the old per-batch
        # ``batch.device_step`` span would only time the host-side
        # conversion of an already-materialized output here, so it is
        # gone rather than left lying about where the time went.
        outputs = self._chainer.map_stream(results)
        if self.async_fetch:
            # Async completion: each output's D2H copy starts the moment
            # its dispatch lands and is collected while the following
            # dispatches run — readback hides behind compute instead of
            # serializing with it. Bitwise-identical to the blocking
            # path; a device error still surfaces on ITS batch.
            outputs = AsyncFetcher(
                window=self._fetch_window(), path="batch"
            ).stream(outputs)
        for i, out in enumerate(outputs):
            n = metas[i]
            if isinstance(out, (tuple, list)):
                arrays: Any = [np.asarray(o) for o in out]
            else:
                arrays = np.asarray(out)
            if isinstance(arrays, list):
                for j in range(n):
                    yield tuple(a[j] for a in arrays)
            else:
                yield from arrays[:n]

    def _chain_floor(self) -> int:
        """The chain ceiling the staging depth must cover: the RESOLVED
        chain_k (env override included), or the policy ceiling in auto
        mode since K can ramp there after the first measured dispatch."""
        return self._chainer.chain_k or self._chainer.policy.max_chain

    def _feed_depth(self) -> int:
        """Staging depth: a K-chain consumes K staged batches per
        dispatch, so the pipeline must run at least that far ahead or
        the chain assembly itself becomes the serialization point."""
        return max(self._prefetch_depth, self._chain_floor())

    def _tuning_knobs(self, prefix: str) -> "list[Any]":
        """This runner's non-stage knobs for the autotuner: the dispatch
        chain K (inverted — it grows when the CONSUMER side lags, i.e.
        producer-blocked, to amortize per-dispatch overhead) and the
        native packer parallelism. Pinned chain lengths (explicit
        ``chain_k=`` or ``SPARKDL_TPU_CHAIN_K``) register pinned so the
        gauge still exports them but the tuner never moves them."""
        from sparkdl_tpu.ingest.autotune import Knob
        from sparkdl_tpu.native import bridge

        ch = self._chainer

        def get_k(ch=ch) -> int:
            return int(ch.chain_k if ch.chain_k is not None
                       else ch.policy.chain_len())

        def set_k(v: int, ch=ch) -> None:
            # map_stream consults target_chain_len() per item, so a live
            # chain_k write takes effect at the next group boundary.
            # Growth is clamped to the ChainPolicy's overhead-aware
            # recommendation: chaining past the K that already holds the
            # dispatch-gap share under target buys nothing and only
            # delays host visibility — on a backend with a negligible
            # gap (local CPU) the recommendation is 1 and the tuner's
            # grow is a no-op the read-back check discards.
            ch.chain_k = max(1, min(int(v), ch.policy.chain_len()))

        knobs = [Knob(
            name=f"{prefix}.chain_k", get=get_k, set=set_k,
            lo=1, hi=ch.policy.max_chain, inverted=True,
            pinned=ch.pinned, pin_source=ch.pin_source,
        )]
        # the pack-thread knob deliberately keeps its process-global
        # name: it closes over module-global state shared by every
        # stream, so all registrations ARE the same knob
        knobs.extend(bridge.pack_knobs())
        return knobs

    def _device_feed(
        self, host_batches: Iterator[dict[str, np.ndarray]]
    ) -> Iterator[dict[str, Any]]:
        """Stage host batch dicts onto the device with transfer/compute
        overlap; picks the native ring when it applies. (The streaming
        entry is :meth:`run`'s pipeline — this is the same ``to_device``
        stage exposed for direct feeds and introspection.)"""
        from sparkdl_tpu.ingest.pipeline import _ToDeviceStage

        stage = _ToDeviceStage(
            self._transfer, self._feed_depth(), self.ragged_rows,
            max(self._buckets), None, "device",
            pinned=self._prefetch_pinned,
        )
        return iter(stage.build(iter(host_batches), None))

    def run_batch(self, arrays: dict[str, np.ndarray]):
        """One-shot dispatch for the online serving path: pad the stacked
        batch to its bucket, stage it (dp-sharded on multi-chip hosts —
        the same ``_transfer`` the streaming path uses), run the SAME
        jitted program the batch path compiled, and unpad.

        Returns the output array [n, ...] (or a tuple of arrays for
        multi-output apply_fns). An empty input (a serving flush tick)
        still runs the smallest-bucket program — pad_to_bucket zero-fills
        it — so the outputs keep their real dtypes and feature shapes,
        just with 0 rows.
        """
        return self.run_batch_async(arrays).result()

    def run_batch_async(self, arrays: dict[str, np.ndarray]) -> "BatchResult":
        """The future-returning :meth:`run_batch`: dispatch now, start
        the async D2H copy, and hand back a :class:`BatchResult` whose
        ``result()`` blocks only for whatever copy time is left. The
        micro-batcher pipelines on this — it assembles and dispatches
        the NEXT micro-batch while the previous one's readback lands.
        Dispatch/occupancy semantics are identical to :meth:`run_batch`
        (one request group = one dispatch, never chained)."""
        fault_point("dispatch")
        padded = pad_to_bucket(arrays, self._buckets)
        t0 = time.perf_counter()
        with span("serving.device_step", rows=padded.n_valid,
                  bucket=padded.bucket):
            # one request group = one dispatch, NEVER chained: chaining
            # would couple unrelated requests' failure domains, and the
            # micro-batcher already amortizes dispatch across riders
            out = self._jitted(self._transfer(padded.arrays))
            ticket = start_fetch(out, path="serving")
        return BatchResult(ticket, padded.n_valid, t0)

    def _transfer(self, arrays: dict[str, np.ndarray]):
        # the partitioner owns placement: dp meshes commit one shard per
        # local chip (jit compiles the apply SPMD from the sharding),
        # pinned replicas commit to their device, single-device stays
        # the plain uncommitted put. check=False: every batch through
        # here is already padded to a bucket rounded to the data axes
        return self._partitioner.shard_batch(arrays, check=False)


class BatchResult:
    """In-flight :meth:`BatchedRunner.run_batch_async` result.

    ``result()`` collects the host output (unpadded to the live rows),
    records the dispatch into the spine exactly once, and re-raises
    this batch's device error if its program failed. Thread-safe and
    idempotent, so the micro-batcher may resolve from any thread; a
    fallback-pool timeout is not terminal (the result stays
    collectable).

    Metric semantics: the recorded ``sparkdl_dispatch_seconds`` wall
    spans dispatch to COLLECTION — when resolution is pipelined (the
    micro-batcher keeps ``max_inflight`` batches open) it includes the
    bounded residency behind the predecessors, so the serving wall
    histogram reads as pipeline latency, not pure device time (the
    count stays exact; overhead_share only gets more conservative).
    The synchronous :meth:`BatchedRunner.run_batch` resolves
    immediately and keeps the old pure-dispatch wall."""

    __slots__ = ("_ticket", "_n_valid", "_t0", "_done", "_value", "_exc",
                 "_lock")

    def __init__(self, ticket: FetchTicket, n_valid: int, t0: float):
        self._ticket = ticket
        self._n_valid = n_valid
        self._t0 = t0
        self._done = False
        self._value: Any = None
        self._exc: "BaseException | None" = None
        self._lock = threading.Lock()

    def result(self, timeout: "float | None" = None):
        with self._lock:
            if not self._done:
                try:
                    out = self._ticket.result(timeout)
                except FuturesTimeoutError:
                    raise  # not terminal: collect again later
                except BaseException as e:
                    self._exc = e
                else:
                    if isinstance(out, (tuple, list)):
                        self._value = tuple(
                            np.asarray(o)[: self._n_valid] for o in out
                        )
                    else:
                        self._value = np.asarray(out)[: self._n_valid]
                self._done = True
                record_dispatch(
                    "serving", 1, time.perf_counter() - self._t0
                )
            if self._exc is not None:
                raise self._exc
            return self._value


#: graph object -> {cache key: BatchedRunner}; weak so graphs can be GC'd.
_GRAPH_RUNNERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def cached_graph_runner(graph, key, make_apply_fn: Callable[[], Callable],
                        batch_size: int,
                        ragged_rows: bool = False) -> BatchedRunner:
    """Process-wide BatchedRunner cache keyed by (graph identity, key).

    One jax.jit per (ingested graph, shape/batch config) no matter how many
    partitions, transformer copies, or transformer classes touch it.
    """
    per_graph = _GRAPH_RUNNERS.setdefault(graph, {})
    if key not in per_graph:
        per_graph[key] = BatchedRunner(
            make_apply_fn(), batch_size=batch_size, ragged_rows=ragged_rows
        )
    return per_graph[key]


def try_extract(extract: Callable[[Any], dict[str, np.ndarray]],
                row: Any) -> "tuple[dict[str, np.ndarray] | None, Exception | None]":
    """Run ``extract`` on one row, capturing the error instead of raising —
    the single bad-row convention shared by the batch partition path and
    the online micro-batcher: a row that cannot be featurized degrades to
    a per-row error and never poisons its batch."""
    try:
        return extract(row), None
    except Exception as e:
        return None, e


def run_partition_with_passthrough(
    rows: "list[dict]",
    extract: Callable[[dict], dict[str, np.ndarray]],
    runner: BatchedRunner,
    output_col: str,
    postprocess: Callable[[np.ndarray], Any] | None = None,
    input_cols: "tuple[str, ...] | None" = None,
) -> Iterator[dict]:
    """Run inference for a partition, appending ``output_col`` to each row.

    ``extract`` turns a row into the numeric feature dict the model eats;
    rows it raises on are yielded unchanged with output None (mirrors the
    reference's tolerance of undecodable rows). Misconfiguration stays loud
    rather than masked as bad data: missing ``input_cols`` raise
    immediately, and an all-rows-failed partition logs a warning with the
    first error.
    """
    if rows and input_cols:
        missing = [c for c in input_cols if c not in rows[0]]
        if missing:
            raise KeyError(
                f"input column(s) {missing} not in DataFrame columns "
                f"{sorted(rows[0].keys())}"
            )
    feeds: list[dict[str, np.ndarray] | None] = []
    first_error: Exception | None = None
    for r in rows:
        feed, err = try_extract(extract, r)
        first_error = first_error or err
        feeds.append(feed)
    valid = [f for f in feeds if f is not None]
    if rows and not valid and first_error is not None:
        logging.getLogger(__name__).warning(
            "all %d rows in partition failed extraction (output=None); "
            "first error: %r", len(rows), first_error,
        )
    outputs = runner.run(iter(valid)) if valid else iter(())
    for r, f in zip(rows, feeds):
        out_row = dict(r)
        if f is None:
            out_row[output_col] = None
        else:
            o = next(outputs)
            out_row[output_col] = postprocess(o) if postprocess else o
        yield out_row
