"""DeepTextGenerator — GPT generation over DataFrames.

Serving-side sibling of :class:`DeepTextFeaturizer` (the reference has no
text models at all — SURVEY.md 2.1 — but its transformer surface invites
exactly this class): a column of prompt token-id arrays goes in, a column
of generated token ids comes out. Unequal-length prompts in a batch
decode TOGETHER via the ragged left-padded ``generate`` path
(models/gpt.py): pad columns are excluded from every attention softmax,
so each row's output equals its unbatched decode (greedy) while the whole
batch shares one KV-cached ``lax.scan``.

Execution shape: prompts bucket by (batch rows, padded prompt length) so
each jitted generate program compiles once per bucket; on a multi-chip
host the batch lands dp-sharded (``runtime.mesh.batch_sharding``) and the
prefill + decode scan run SPMD — the same committed-input-sharding
mechanism as BatchedRunner's data-parallel inference. Tokenization is
upstream (bring your own tokenizer), mirroring the featurizer.
"""

from __future__ import annotations

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.runtime.batching import default_buckets, pick_bucket
from sparkdl_tpu.transformers._inference import (
    run_partition_with_passthrough,
)
from sparkdl_tpu.transformers.text import _fingerprint, _LruCache

#: per-process jitted-generate cache, LRU-bounded like the featurizer's
#: runner cache (key: weights fingerprint + config + decode params).
_GEN_CACHE: _LruCache = _LruCache(maxsize=8)


def _to_bundle(value):
    from sparkdl_tpu.models.gpt import GPTConfig

    if (
        isinstance(value, tuple)
        and len(value) == 2
        and isinstance(value[0], GPTConfig)
    ):
        return value
    raise TypeError(
        "model must be a (GPTConfig, variables) tuple, e.g. from "
        "models.gpt.load_hf_gpt2(...) or (cfg, GPTLMHeadModel(cfg).init(...))"
    )


class DeepTextGenerator(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    """prompt token ids (array<int>) -> generated token ids (array<int>).

    ``temperature=0`` (default) decodes greedily — deterministic, and each
    row matches its unbatched decode. ``temperature>0`` samples with
    optional ``topK``/``topP``; draws are deterministic per (seed, batch),
    so re-running a partition reproduces its outputs.
    """

    model = Param(None, "model", "(GPTConfig, variables) decoder bundle",
                  _to_bundle)
    maxNewTokens = Param(None, "maxNewTokens",
                         "number of tokens to generate per row",
                         SparkDLTypeConverters.toInt)
    maxLength = Param(
        None, "maxLength",
        "prompt cap: longer prompts keep their LAST maxLength tokens "
        "(the continuation-relevant tail)", SparkDLTypeConverters.toInt)
    temperature = Param(None, "temperature",
                        "0 = greedy; >0 = sampled softmax temperature",
                        SparkDLTypeConverters.toFloat)
    topK = Param(None, "topK", "sample from the top-K logits only",
                 SparkDLTypeConverters.toInt)
    topP = Param(None, "topP", "nucleus sampling mass in (0, 1]",
                 SparkDLTypeConverters.toFloat)
    seed = Param(None, "seed", "sampling seed", SparkDLTypeConverters.toInt)

    def __init__(self, inputCol=None, outputCol=None, model=None,
                 maxNewTokens=None, maxLength=None, temperature=None,
                 topK=None, topP=None, seed=None, batchSize=None):
        super().__init__()
        self._setDefault(maxNewTokens=32, maxLength=128, temperature=0.0,
                         seed=0, batchSize=16)
        self._set(inputCol=inputCol, outputCol=outputCol, model=model,
                  maxNewTokens=maxNewTokens, maxLength=maxLength,
                  temperature=temperature, topK=topK, topP=topP, seed=seed,
                  batchSize=batchSize)

    def setModel(self, value):
        return self._set(model=value)

    def _transform(self, dataset):
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.models.gpt import GPTLMHeadModel, generate

        cfg, variables = self.getOrDefault("model")
        max_new = self.getOrDefault("maxNewTokens")
        max_len = self.getOrDefault("maxLength")
        temperature = self.getOrDefault("temperature")
        top_k = (self.getOrDefault("topK")
                 if self.isDefined("topK") else None)
        top_p = (self.getOrDefault("topP")
                 if self.isDefined("topP") else None)
        seed = self.getOrDefault("seed")
        batch_size = self.getBatchSize()
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        if cfg.positions == "learned" and max_len + max_new > cfg.max_seq_len:
            raise ValueError(
                f"maxLength {max_len} + maxNewTokens {max_new} exceeds the "
                f"learned position table (max_seq_len={cfg.max_seq_len}); "
                "lower them or use a RoPE config"
            )
        if temperature <= 0 and (top_k is not None or top_p is not None):
            # fail fast on the driver; generate() would raise the same
            # contract deep inside partition execution
            raise ValueError(
                "topK/topP only apply when sampling — set temperature > 0"
            )
        model = GPTLMHeadModel(cfg)

        len_buckets = default_buckets(max_len, min_bucket=8)

        def make_generate_fn():
            # one jit per (rows, prompt_len) bucket, cached process-wide;
            # mask validation is ours (left-padded by construction)
            @jax.jit
            def run(variables, ids, mask, key):
                return generate(
                    model, variables, ids, max_new,
                    attention_mask=mask, temperature=temperature,
                    top_k=top_k, top_p=top_p,
                    rng=key if temperature > 0 else None,
                )

            return run

        def extract(row):
            ids = np.asarray(row[input_col], dtype=np.int32)
            if ids.ndim != 1 or ids.size == 0:
                raise ValueError(
                    f"prompt must be a non-empty 1-D id array, got shape "
                    f"{ids.shape}")
            return ids[-max_len:]  # keep the continuation-relevant tail

        class _GenRunner:
            """run_partition_with_passthrough adapter: groups prompts,
            buckets (rows, prompt_len) per group, generates, yields the
            per-row generated ids in order."""

            def __init__(self, run, sharding, chunk, row_buckets):
                self._run = run
                self._sharding = sharding
                self._chunk = chunk
                self._row_buckets = row_buckets

            def run(self, prompts):
                valid = list(prompts)
                rng_counter = 0
                for start in range(0, len(valid), self._chunk):
                    group = valid[start:start + self._chunk]
                    nb = pick_bucket(len(group), self._row_buckets)
                    lp = pick_bucket(max(len(g) for g in group),
                                     len_buckets)
                    ids = np.zeros((nb, lp), np.int32)
                    mask = np.zeros((nb, lp), np.int32)
                    for i, g in enumerate(group):
                        ids[i, lp - len(g):] = g
                        mask[i, lp - len(g):] = 1
                    mask[len(group):, -1] = 1  # pad rows: 1 real token
                    if self._sharding is not None:
                        # one sharded H2D transfer straight from numpy
                        jids = jax.device_put(ids, self._sharding)
                        jmask = jax.device_put(mask, self._sharding)
                    else:
                        jids, jmask = jnp.asarray(ids), jnp.asarray(mask)
                    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                             rng_counter)
                    rng_counter += 1
                    out = np.asarray(self._run(variables, jids, jmask, key))
                    yield from (out[i, lp:] for i in range(len(group)))

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            key = (_fingerprint(variables), cfg, max_new, max_len,
                   temperature, top_k, top_p, batch_size)
            run = _GEN_CACHE.get(key)
            if run is None:
                run = _GEN_CACHE[key] = make_generate_fn()

            # BatchedRunner's dp bucket discipline: round the chunk size
            # DOWN to a device multiple, buckets up to multiples, so full
            # groups hit their bucket exactly (no steady-state pad rows
            # and one compile per bucket, not per device-count remainder)
            n_local = jax.local_device_count()
            sharding = None
            chunk = batch_size
            row_buckets = default_buckets(batch_size, min_bucket=4)
            n_use = max(1, min(n_local, batch_size))
            if n_use > 1:
                from sparkdl_tpu.runtime.mesh import (
                    batch_sharding,
                    data_parallel_mesh,
                )

                sharding = batch_sharding(
                    data_parallel_mesh(jax.local_devices()[:n_use]))
                chunk = max(n_use, batch_size // n_use * n_use)
                row_buckets = sorted({
                    -(-b // n_use) * n_use
                    for b in default_buckets(chunk, min_bucket=4)
                })

            runner = _GenRunner(run, sharding, chunk, row_buckets)
            return run_partition_with_passthrough(
                rows, extract, runner, output_col,
                postprocess=lambda o: np.asarray(o).tolist(),
                input_cols=(input_col,),
            )

        return transform_partitions(
            dataset, partition_fn, [(output_col, "array<int>")]
        )
