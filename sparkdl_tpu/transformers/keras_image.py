"""KerasImageFileTransformer — Keras inference over a column of image URIs.

Reference parity (SURVEY.md 2.4, [U: python/sparkdl/transformers/
keras_image.py]): a user-supplied ``imageLoader(uri) -> np.ndarray`` runs per
row (load + preprocess to the model's input shape), then the Keras model
scores the loaded batch. The model executes natively on JAX (Keras 3 jax
backend) through the shared bucketed/prefetched runner.
"""

from __future__ import annotations

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import run_partition_with_passthrough
from sparkdl_tpu.transformers.keras_tensor import _keras_runner


class CanLoadImage:
    """Mixin: the ``imageLoader`` param shared by the image-file APIs
    ([U: python/sparkdl/param/image_params.py] CanLoadImage)."""

    imageLoader = Param(
        None, "imageLoader",
        "callable uri -> np.ndarray loading and preprocessing one image",
    )

    def getImageLoader(self):
        return self.getOrDefault("imageLoader")

    def loadImage(self, uri: str) -> np.ndarray:
        loader = self.getImageLoader()
        if loader is None:
            raise ValueError("imageLoader is not set")
        return np.asarray(loader(uri))


class KerasImageFileTransformer(
    Transformer, CanLoadImage, HasInputCol, HasOutputCol, HasBatchSize
):
    modelFile = Param(
        None, "modelFile", "path to the Keras model (.h5 or .keras)",
        SparkDLTypeConverters.toExistingFilePath,
    )

    def __init__(self, inputCol=None, outputCol=None, modelFile=None,
                 imageLoader=None, batchSize=None):
        super().__init__()
        self._setDefault(batchSize=32)
        self._set(inputCol=inputCol, outputCol=outputCol, modelFile=modelFile,
                  imageLoader=imageLoader, batchSize=batchSize)

    def setModelFile(self, value: str):
        return self._set(modelFile=value)

    def getModelFile(self) -> str:
        return self.getOrDefault("modelFile")

    def _transform(self, dataset):
        import os

        model_file = self.getModelFile()
        mtime = os.path.getmtime(model_file)
        batch_size = self.getBatchSize()
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        loader = self.getImageLoader()
        if loader is None:
            raise ValueError("imageLoader is not set")

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            runner = _keras_runner(model_file, mtime, batch_size)

            def extract(row):
                arr = np.asarray(loader(row[input_col]), dtype=np.float32)
                # loaders may emit a leading batch dim of 1; strip it
                if arr.ndim == 4 and arr.shape[0] == 1:
                    arr = arr[0]
                return {"x": arr}

            return run_partition_with_passthrough(
                rows, extract, runner, output_col,
                lambda o: np.asarray(o, dtype=np.float32).reshape(-1),
                input_cols=(input_col,),
            )

        return transform_partitions(
            dataset, partition_fn, [(output_col, "array<float>")]
        )
