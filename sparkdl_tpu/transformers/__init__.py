from sparkdl_tpu.transformers.named_image import (
    DeepImageFeaturizer,
    DeepImagePredictor,
)
from sparkdl_tpu.transformers.keras_tensor import KerasTransformer
from sparkdl_tpu.transformers.text import DeepTextFeaturizer
from sparkdl_tpu.transformers.text_generator import DeepTextGenerator

__all__ = [
    "DeepImageFeaturizer",
    "DeepImagePredictor",
    "KerasTransformer",
    "DeepTextFeaturizer",
    "DeepTextGenerator",
]
