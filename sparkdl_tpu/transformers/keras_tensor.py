"""KerasTransformer — batch inference with a user Keras model over a
column of 1-D numeric arrays.

Reference parity (SURVEY.md 2.3, [U: python/sparkdl/transformers/
keras_tensor.py]): the reference loads the HDF5 model, freezes it to a TF
GraphDef and runs it via TFTransformer. Here the model executes natively on
JAX (Keras 3 jax backend): ``stateless_call`` is a pure function of the
weights and inputs, so it jits and shards like any other JAX code — no
freezing step exists or is needed.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from sparkdl_tpu.dataframe import transform_partitions
from sparkdl_tpu.param import (
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
    SparkDLTypeConverters,
    Transformer,
)
from sparkdl_tpu.transformers._inference import (
    BatchedRunner,
    run_partition_with_passthrough,
)


@functools.lru_cache(maxsize=16)
def _load_keras_predictor(model_file: str, mtime: float):
    """Per-process cache: load the model once per (file, mtime).

    Returns ``predict(batch_dict) -> np.ndarray`` built on stateless_call
    when Keras runs on the jax backend, else a plain __call__ fallback.
    """
    import keras

    model = keras.models.load_model(model_file, compile=False)
    if keras.backend.backend() == "jax":
        trainable = [v.value for v in model.trainable_variables]
        non_trainable = [v.value for v in model.non_trainable_variables]

        def apply_fn(batch):
            y, _ = model.stateless_call(
                trainable, non_trainable, batch["x"], training=False
            )
            return y

        return apply_fn, True
    # Non-jax Keras backend (user overrode KERAS_BACKEND): still correct,
    # not jit-compiled.
    def apply_np(batch):
        return np.asarray(model(batch["x"], training=False))

    return apply_np, False


@functools.lru_cache(maxsize=16)
def _keras_runner(model_file: str, mtime: float, batch_size: int):
    """Per-process runner cache: one jax.jit per (model file, batch size),
    shared across partitions so XLA compiles each bucket exactly once."""
    apply_fn, jittable = _load_keras_predictor(model_file, mtime)
    if jittable:
        return BatchedRunner(apply_fn, batch_size=batch_size)
    return _EagerRunner(apply_fn, batch_size)


class KerasTransformer(Transformer, HasInputCol, HasOutputCol, HasBatchSize):
    modelFile = Param(
        None, "modelFile", "path to the Keras model (.h5 or .keras)",
        SparkDLTypeConverters.toExistingFilePath,
    )

    def __init__(self, inputCol=None, outputCol=None, modelFile=None,
                 batchSize=None):
        super().__init__()
        self._setDefault(batchSize=256)
        self._set(inputCol=inputCol, outputCol=outputCol, modelFile=modelFile,
                  batchSize=batchSize)

    def setModelFile(self, value: str):
        return self._set(modelFile=value)

    def getModelFile(self) -> str:
        return self.getOrDefault("modelFile")

    def _transform(self, dataset):
        model_file = self.getModelFile()
        mtime = os.path.getmtime(model_file)
        batch_size = self.getBatchSize()
        input_col = self.getInputCol()
        output_col = self.getOutputCol()

        def partition_fn(rows):
            rows = list(rows)
            if not rows:
                return iter(())
            runner = _keras_runner(model_file, mtime, batch_size)

            def extract(row):
                arr = np.asarray(row[input_col], dtype=np.float32)
                if arr.ndim != 1:
                    raise ValueError(
                        f"KerasTransformer input must be 1-D, got {arr.shape}"
                    )
                return {"x": arr}

            return run_partition_with_passthrough(
                rows, extract, runner, output_col,
                lambda o: np.asarray(o, dtype=np.float32),
                input_cols=(input_col,),
            )

        return transform_partitions(
            dataset, partition_fn, [(output_col, "array<float>")]
        )


class _EagerRunner:
    """BatchedRunner-shaped wrapper for non-jittable backends.

    No bucket padding: padding exists to protect jit's shape-keyed compile
    cache, which the eager path doesn't have — tails run at natural size.
    """

    def __init__(self, apply_fn, batch_size: int):
        self.apply_fn = apply_fn
        self.batch_size = batch_size

    def run(self, rows):
        pending = []
        for r in rows:
            pending.append(r)
            if len(pending) == self.batch_size:
                yield from self._flush(pending)
                pending = []
        if pending:
            yield from self._flush(pending)

    def _flush(self, pending):
        arrays = {
            k: np.stack([r[k] for r in pending]) for k in pending[0].keys()
        }
        yield from np.asarray(self.apply_fn(arrays))
