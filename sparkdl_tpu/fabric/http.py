"""Thin HTTP/json transport for fabric hosts.

Real deployments put one serving process per host behind the router
tier; this module is the wire between them, built on the SAME stdlib
``http.server`` machinery as the metrics exporter (zero dependencies,
daemon threads, ThreadingHTTPServer). It is deliberately *thin*: one
blocking POST per request (the client side wraps it in a small thread
pool to give the router Futures), json bodies, no streaming — the
fabric's contracts (affinity, spillover, drain, failover) live in the
router and are transport-agnostic, which is why the in-process handle
and this one are interchangeable in every test.

Server endpoints (:class:`HostServer`, wrapping one engine):

* ``POST /fabric/submit``  ``{"prompt": [...], "max_new_tokens": n,
  "timeout_s": t|null}`` → ``{"tokens": [...], "request_id": id}``;
  errors answer non-200 with ``{"error": <type>, "message": ...}`` and
  map back to typed exceptions client-side (429 QueueFull, 503
  closed/draining, 504 deadline). Disaggregated tiers (ISSUE 16) ride
  the same endpoint: a ``{"handoff": <KVHandoff wire dict>}`` body
  installs on a decode-tier engine, and a prefill-tier engine answers
  ``{"handoff": ...}`` instead of tokens — the quantized KV blocks
  cross hosts base64-encoded in their RAW pool storage, so an int8
  tier's wire cost stays ~4× under fp32's.
* ``GET /fabric/snapshot`` → ``engine.snapshot()`` (host_id + capacity
  included — the router's weighting input).
* ``GET /fabric/digest`` → ``engine.prefix_digest()`` (null for dense).
* ``GET /fabric/digest_delta?since=N`` → ``{"delta": ...}`` — the
  block-hash journal since version N (ISSUE 19), null when the host
  cannot produce one (gap, dense, no journal): the router re-syncs
  with one wholesale ``/fabric/digest``.
* ``POST /fabric/migrate_out`` → ``{"bundle": ...}`` (the host's
  parked sessions, serialized through the handoff raw-storage codec)
  and ``POST /fabric/migrate_in`` ``{"bundle": ...}`` →
  ``{"imported": n}`` — the two wire ends of parked-session migration
  on drain/scale-down (ISSUE 19).
* ``GET /fabric/trace?request_id=N`` → this host's span fragments for
  one trace plus its trace-clock reading (``now_us``) — the
  :class:`~sparkdl_tpu.observability.fleet.FleetScraper`'s stitching
  RPC (ISSUE 17). Submit bodies may carry a serialized ``"trace"``
  span context; the server attaches it so host-side spans parent into
  the CALLER's trace instead of starting an orphan.
* ``GET /fabric/healthz`` → the process ``healthz_report()`` (one
  engine per process in real deployments, so process grain == host
  grain here).
* ``POST /fabric/drain`` → stops admission, fails every unstarted
  request with :class:`~sparkdl_tpu.fabric.host.HostDrainingError` so
  the blocked client submits return and the router's failover path
  re-places them on surviving hosts. The drain is NOT a request
  failure: nothing lands in ``sparkdl_requests_failed_total`` (the
  no-double-count contract — the re-routed request is counted, once,
  by whatever finally happens to it on its new host).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from sparkdl_tpu.observability import flight, tracing
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.serving.queue import (
    DeadlineExceededError,
    EngineClosedError,
    QueueFullError,
)

from sparkdl_tpu.fabric.host import (
    HostDrainingError,
    HostHandle,
    HostUnavailableError,
)

__all__ = ["HostServer", "HttpHostHandle"]

_log = logging.getLogger(__name__)

#: error-name → (exception type, HTTP status) map shared by both ends
#: of the wire so a remote failure re-raises as the SAME type the
#: in-process engine would have raised (the router's retry classes must
#: not care which transport a host sits behind)
_ERROR_TYPES = {
    "QueueFullError": (QueueFullError, 429),
    "EngineClosedError": (EngineClosedError, 503),
    "HostDrainingError": (HostDrainingError, 503),
    "DeadlineExceededError": (DeadlineExceededError, 504),
    "ValueError": (ValueError, 400),
}


def _register_handoff_errors() -> None:
    """Add the disagg tier's typed error to the wire map on first
    handoff use — not at import, so the transport never drags the
    disagg package (and the model stack behind it) into processes that
    only route plain prompts. The PhaseRouter's zero-loss requeue keys
    on the typed re-raise, so it must survive the wire."""
    if "HandoffInstallError" not in _ERROR_TYPES:
        from sparkdl_tpu.disagg.handoff import HandoffInstallError

        _ERROR_TYPES["HandoffInstallError"] = (HandoffInstallError, 409)


def _status_for(exc: BaseException) -> "tuple[str, int]":
    for name, (typ, status) in _ERROR_TYPES.items():
        if isinstance(exc, typ):
            return name, status
    return type(exc).__name__, 500


class _FabricHandler(BaseHTTPRequestHandler):
    server_owner: "HostServer"  # set on the per-instance subclass

    def _reply(self, status: int, body: dict) -> None:
        data = json.dumps(body, default=repr).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_error(self, exc: BaseException) -> None:
        name, status = _status_for(exc)
        self._reply(status, {"error": name, "message": str(exc)})

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path, _, query = self.path.partition("?")
        owner = self.server_owner
        try:
            if path == "/fabric/snapshot":
                self._reply(200, owner.engine.snapshot())
            elif path == "/fabric/digest":
                params = urllib.parse.parse_qs(query)
                n = int(params.get("max_entries", ["1024"])[0])
                dig = owner.engine.prefix_digest(n)
                self._reply(200, {"digest": dig})
            elif path == "/fabric/digest_delta":
                params = urllib.parse.parse_qs(query)
                since = int(params.get("since", ["0"])[0])
                n = int(params.get("max_entries", ["1024"])[0])
                fn = getattr(owner.engine, "prefix_digest_delta", None)
                delta = fn(since, n) if callable(fn) else None
                self._reply(200, {"delta": delta})
            elif path == "/fabric/trace":
                params = urllib.parse.parse_qs(query)
                rid = int(params.get("request_id", ["0"])[0])
                self._reply(200, {
                    "host_id": owner.engine.host_id,
                    # trace-clock reading WHILE serving: pairs with the
                    # caller's RPC round-trip midpoint for clock-offset
                    # estimation (fleet stitching, ISSUE 17)
                    "now_us": tracing.trace_clock_us(),
                    "spans": owner.handle_trace(rid),
                })
            elif path == "/fabric/healthz":
                from sparkdl_tpu.observability.flight import healthz_report

                report = healthz_report()
                report["host_id"] = owner.engine.host_id
                report["draining"] = owner.draining
                self._reply(
                    503 if report["status"] == "unhealthy" else 200,
                    report)
            else:
                self.send_error(404)
        except Exception as e:  # transport must answer, never hang
            _log.exception("fabric: %s handler failed", path)
            self._reply_error(e)

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        owner = self.server_owner
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": "ValueError", "message": str(e)})
            return
        try:
            if path == "/fabric/submit":
                self._reply(200, owner.handle_submit(body))
            elif path == "/fabric/drain":
                self._reply(200, owner.handle_drain())
            elif path == "/fabric/migrate_out":
                fn = getattr(owner.engine, "export_parked_sessions",
                             None)
                bundle = fn() if callable(fn) else None
                self._reply(200, {"bundle": bundle})
            elif path == "/fabric/migrate_in":
                fn = getattr(owner.engine, "import_parked_sessions",
                             None)
                n = (int(fn(body.get("bundle")))
                     if callable(fn) else 0)
                self._reply(200, {"imported": n})
            else:
                self.send_error(404)
        except Exception as e:
            self._reply_error(e)

    def log_message(self, fmt, *args):  # no stdout spam per request
        _log.debug("fabric: " + fmt, *args)


class HostServer:
    """Serve one engine's fabric surface over HTTP (daemon threads).

    ``result_timeout_s`` bounds how long one submit's worker thread
    blocks on the engine before answering 504 — the transport-level
    backstop under a caller that sent no ``timeout_s``."""

    def __init__(self, engine: Any, *, port: int = 0, host: str = "",
                 result_timeout_s: float = 120.0):
        self.engine = engine
        self.result_timeout_s = result_timeout_s
        self.draining = False
        handler = type("_BoundFabricHandler", (_FabricHandler,),
                       {"server_owner": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name=f"sparkdl-fabric-host-{engine.host_id}", daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- request handling (called from handler threads) ----------------------
    def handle_submit(self, body: dict) -> dict:
        if self.draining:
            raise HostDrainingError(
                f"host {self.engine.host_id} is draining")
        timeout_s = body.get("timeout_s")
        timeout = float(timeout_s) if timeout_s is not None else None
        if "handoff" in body:
            # decode-tier admission (ISSUE 16): install the transferred
            # blocks, no re-prefill
            _register_handoff_errors()
            from sparkdl_tpu.disagg.handoff import KVHandoff

            fut = self.engine.submit_handoff(
                KVHandoff.from_wire(body["handoff"]), timeout_s=timeout)
        else:
            prompt = np.asarray(body["prompt"], np.int32)
            # a shipped span context (ISSUE 17) parents this host's
            # request trace into the CALLER's — the submit span the
            # queue records links back across the process boundary
            with tracing.attach(
                    tracing.context_from_wire(body.get("trace"))):
                fut = self.engine.submit(
                    prompt, int(body["max_new_tokens"]),
                    timeout_s=timeout)
        try:
            result = fut.result(timeout=self.result_timeout_s)
        except FuturesTimeoutError:
            # map the backstop to the documented 504/DeadlineExceeded —
            # the raw futures TimeoutError would cross the wire as a
            # 500 and read as a DEAD HOST, re-routing (and duplicating)
            # a generation that is merely slow
            raise DeadlineExceededError(
                f"generation exceeded the host result backstop "
                f"({self.result_timeout_s}s)") from None
        rid = getattr(fut, "request_id", None)
        if hasattr(result, "to_wire"):
            # a prefill-tier engine resolves to a KVHandoff: ship it
            return {"handoff": result.to_wire(), "request_id": rid}
        return {
            "tokens": [int(t) for t in np.asarray(result).ravel()],
            "request_id": rid,
        }

    def handle_trace(self, request_id: int) -> "list[dict]":
        """This host's finished spans for one trace (the stitching RPC's
        payload half; the handler adds the clock reading)."""
        fn = getattr(self.engine, "trace", None)
        if callable(fn):
            return fn(int(request_id))
        return tracing.spans_for_trace(int(request_id))

    def handle_drain(self) -> dict:
        self.draining = True
        reqs = self.engine.begin_drain()
        # fail the extracted requests' LOCAL futures with the typed
        # draining error: their callers are the router's blocked submit
        # threads, whose failover re-places the payloads on surviving
        # hosts. Deliberately NOT record_request_failure: a drained
        # request is moving, not dying (the no-double-count contract).
        exc = HostDrainingError(
            f"host {self.engine.host_id} drained this request before "
            "placement; the fabric re-routes it")
        for r in reqs:
            if r.started or r.future.set_running_or_notify_cancel():
                r.future.set_exception(exc)
        flight.record_event(
            "host.drain", host=self.engine.host_id, requeued=len(reqs),
            transport="http")
        return {"host_id": self.engine.host_id, "requeued": len(reqs)}

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    def __enter__(self) -> "HostServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _raise_remote(name: "str | None", message: str) -> None:
    """Re-raise a remote error client-side. A parsed error body is the
    REQUEST's own outcome: known names re-raise typed, unknown names
    (a model RuntimeError, a KeyError from a bad payload) re-raise as
    a plain RuntimeError — deliberately NOT HostUnavailableError, which
    would promote a poison request into the host-level retry class and
    let it quarantine every healthy host it touches. Only a response
    with no parseable error body (``name=None``: a crashed handler, a
    proxy page) indicts the transport."""
    if name is None:
        raise HostUnavailableError(f"remote host error: {message}")
    typ = _ERROR_TYPES.get(name, (None, 0))[0]
    if typ is None:
        raise RuntimeError(f"remote {name}: {message}")
    raise typ(message)


class HttpHostHandle(HostHandle):
    """Router-side handle over a :class:`HostServer`.

    ``submit`` returns a Future backed by a bounded worker pool (one
    blocking POST per in-flight request — the thin-transport trade;
    ``max_inflight`` sizes the pool). Transport failures surface as
    :class:`HostUnavailableError` (a host-level error: the router
    re-routes); typed engine errors re-raise as themselves.
    """

    def __init__(self, base_url: str, *, host_id: "str | None" = None,
                 max_inflight: int = 32, connect_timeout_s: float = 10.0,
                 result_timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.connect_timeout_s = connect_timeout_s
        #: client-side cap on a deadline-less generation POST — matches
        #: the server's own result backstop, NOT connect_timeout_s: a
        #: 15s generation is a slow success, not a dead host
        self.result_timeout_s = result_timeout_s
        if host_id is None:
            host_id = str(self._get("/fabric/snapshot").get("host_id"))
        self.host_id = host_id
        self._pool = ThreadPoolExecutor(
            max_workers=max_inflight,
            thread_name_prefix=f"sparkdl-fabric-{host_id}")

    # -- wire helpers --------------------------------------------------------
    def _request(self, path: str, body: "dict | None" = None,
                 timeout_s: "float | None" = None) -> dict:
        url = self.base_url + path
        data = (json.dumps(body).encode()
                if body is not None else None)
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"},
            method="POST" if body is not None else "GET")
        try:
            with urllib.request.urlopen(
                    req, timeout=(timeout_s if timeout_s is not None
                                  else self.connect_timeout_s)) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except (ValueError, json.JSONDecodeError):
                payload = {}
            _raise_remote(payload.get("error"),
                          payload.get("message", str(e)))
        except urllib.error.URLError as e:
            raise HostUnavailableError(
                f"host {self.host_id} unreachable at {url}: {e.reason}"
            ) from e

    def _get(self, path: str) -> dict:
        return self._request(path)

    # -- HostHandle surface --------------------------------------------------
    def submit(self, payload: "dict[str, Any]", *,
               timeout_s: "float | None" = None) -> Future:
        fault_point("host.submit")
        if isinstance(payload, dict) and "handoff" in payload:
            # cross-tier KV transfer (ISSUE 16): serialize the handoff
            # for the wire; the install failure must re-raise typed
            _register_handoff_errors()
            body: dict = {"handoff": payload["handoff"].to_wire(),
                          "timeout_s": timeout_s}
        else:
            body = {
                "prompt": [int(t) for t in payload["prompt"]],
                "max_new_tokens": int(payload["max_new_tokens"]),
                "timeout_s": timeout_s,
            }
            # capture the ambient span HERE (the caller's thread) — the
            # pool thread that sends the POST has no contextvar state
            trace = tracing.context_to_wire(tracing.current_context())
            if trace is not None:
                body["trace"] = trace

        def call():
            out = self._request(
                "/fabric/submit", body,
                # the POST blocks for the full generation: give it the
                # request's own deadline (or the result backstop) plus
                # transport headroom — never the bare connect timeout,
                # which would misread a long generation as a dead host
                timeout_s=((timeout_s if timeout_s is not None
                            else self.result_timeout_s)
                           + self.connect_timeout_s))
            if "handoff" in out:
                # a prefill-tier host answered with the exported blocks
                from sparkdl_tpu.disagg.handoff import KVHandoff

                return KVHandoff.from_wire(out["handoff"])
            return np.asarray(out["tokens"], np.int32)

        return self._pool.submit(call)

    def snapshot(self) -> "dict[str, Any]":
        return self._get("/fabric/snapshot")

    def capacity(self) -> "dict[str, Any]":
        return self.snapshot().get("capacity") or {}

    def health(self) -> "dict[str, Any]":
        try:
            return self._get("/fabric/healthz")
        except HostUnavailableError:
            # an unhealthy remote answers 503 WITH a body (handled in
            # _request via the HTTPError branch); no answer at all is
            # this stronger verdict
            return {"status": "unhealthy", "host_id": self.host_id,
                    "unreachable": True}

    def prefix_digest(self, max_entries: int = 1024) -> "dict | None":
        return self._get(
            f"/fabric/digest?max_entries={int(max_entries)}"
        ).get("digest")

    def prefix_digest_delta(self, since_version: int,
                            max_entries: int = 1024) -> "dict | None":
        return self._get(
            f"/fabric/digest_delta?since={int(since_version)}"
            f"&max_entries={int(max_entries)}"
        ).get("delta")

    def export_parked_sessions(self) -> "dict | None":
        # migration can ship many blocks: give it the result budget,
        # not the bare connect timeout
        return self._request(
            "/fabric/migrate_out", {},
            timeout_s=self.result_timeout_s).get("bundle")

    def import_parked_sessions(self, bundle: "dict | None") -> int:
        if not bundle:
            return 0
        return int(self._request(
            "/fabric/migrate_in", {"bundle": bundle},
            timeout_s=self.result_timeout_s).get("imported") or 0)

    def trace(self, request_id: int) -> "dict[str, Any]":
        out = self._get(f"/fabric/trace?request_id={int(request_id)}")
        out.setdefault("host_id", self.host_id)
        return out

    def drain(self) -> list:
        fault_point("host.drain")
        out = self._request("/fabric/drain", {})
        flight.record_event(
            "host.drain_requested", host=self.host_id,
            requeued=out.get("requeued"))
        return []  # remote futures fail with HostDrainingError instead

    def close(self, *, timeout_s: "float | None" = 30.0) -> None:
        self._pool.shutdown(wait=False)
