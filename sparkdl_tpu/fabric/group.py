"""Horizontally scaled router tier: N routers, zero shared state.

One :class:`~sparkdl_tpu.fabric.router.Router` process is the fleet's
throughput ceiling and single point of failure — the coordinator
bottleneck the distributed-TF lineage warns about (arXiv 1603.04467).
ISSUE 19's answer is N routers that AGREE without coordinating:

* **Placement agreement is arithmetic, not state.** Every router hashes
  a prompt's first prefix block (``placement_key``) and every host id
  through the same rendezvous function (``hrw_score``); sticky sessions
  hash the session id (``session_key``). Two routers with the same host
  set therefore break every score tie — and derive every session home —
  identically, in any process, with no messages between them.
* **Disagreement windows degrade affinity, never correctness.** Each
  router still keeps its own probation/quarantine/outstanding view
  (health is a local observation, not consensus). While views differ,
  the routers may pick different hosts for the same prompt — costing at
  most one cold prefill on the "wrong" host, exactly what a digest-less
  router pays — and the deterministic tie-break re-converges them as
  soon as the views match again.
* **A dead router loses nothing.** Routers are stateless by
  construction (the LRU is a cache over the hash, digests re-sync from
  the hosts), so :class:`RouterGroup` just skips closed members and
  fails a dispatch over to the next — the chaos bar is kill-one-
  mid-soak with zero lost accepted requests.

:class:`RouterGroup` is the in-process front (tests, single-process
deployments with thread-per-router); :class:`RouterServer` /
:class:`RouterHandle` put one router behind the same stdlib-HTTP
machinery the host tier uses, so a real deployment runs N router
processes behind any dumb TCP balancer.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from concurrent.futures import Future, InvalidStateError, ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Iterable

import numpy as np

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import GaugeShare, registry
from sparkdl_tpu.serving.queue import QueueFullError

from sparkdl_tpu.fabric.digest import session_key
from sparkdl_tpu.fabric.host import HostUnavailableError
from sparkdl_tpu.fabric.http import _raise_remote, _status_for
from sparkdl_tpu.fabric.router import AllHostsUnavailableError, Router

__all__ = [
    "AllRoutersUnavailableError",
    "RouterGroup",
    "RouterHandle",
    "RouterServer",
]

_log = logging.getLogger(__name__)

_M_ROUTERS = registry().gauge(
    "sparkdl_fabric_routers",
    "live routers in the horizontally scaled router tier")
_M_DISPATCH = registry().counter(
    "sparkdl_fabric_router_dispatch_total",
    "requests the router-tier front dispatched, by receiving router",
    labels=("router",))
_M_ROUTER_FAILOVERS = registry().counter(
    "sparkdl_fabric_router_failovers_total",
    "dispatches retried on another router after a router died "
    "mid-dispatch (the kill-one-mid-soak path; host-level failover "
    "inside a live router is sparkdl_fabric_failovers_total)")


class AllRoutersUnavailableError(RuntimeError):
    """Every router in the group is closed or failing; the tier cannot
    dispatch. (Host saturation is NOT this — a healthy router that
    answers :class:`QueueFullError` speaks for the whole fleet.)"""


#: errors that indict the ROUTER (dead process, closed instance, dead
#: transport) rather than the request or the host fleet — the group
#: fails these over to the next member. AllHostsUnavailableError and
#: QueueFullError are deliberately absent: a live router's verdict
#: about the FLEET holds on every other router too.
_ROUTER_LEVEL_ERRORS = (HostUnavailableError, ConnectionError, OSError)


class RouterGroup:
    """Thin stateless front over N routers sharing one host fleet.

    Dispatch picks a deterministic start member — ``session_key(session)
    % n`` for sessions (every front instance starts a session on the
    same router, whose sticky LRU then stays warm), round-robin
    otherwise — and walks the group until a member accepts. A member
    raising a router-level error (closed mid-soak, dead transport) is
    skipped and the dispatch retries on the next; fleet-level verdicts
    (``QueueFullError``, ``AllHostsUnavailableError``) propagate
    immediately, because every live router would say the same thing.

    The group owns no routing state — members stay independently
    usable, and ``close()`` closes only what the caller asks
    (``close_members=True``) since tests often own the routers.
    """

    def __init__(self, routers: "Iterable[Router | Any]"):
        self._routers = list(routers)
        if not self._routers:
            raise ValueError("a RouterGroup needs at least one router")
        self._rr = 0
        self._lock = threading.Lock()
        self._closed = False
        self._g_routers = GaugeShare(_M_ROUTERS)
        self._g_routers.set(len(self._routers))
        flight.record_event(
            "fabric.router_group_start", routers=len(self._routers))

    # -- membership ----------------------------------------------------------
    def routers(self) -> "list[Any]":
        return list(self._routers)

    def live_routers(self) -> "list[Any]":
        return [r for r in self._routers
                if not getattr(r, "closed", False)]

    def _name(self, idx: int) -> str:
        return f"router-{idx}"

    # -- dispatch ------------------------------------------------------------
    def submit(self, payload: Any, *, timeout_s: "float | None" = None,
               session: Any = None) -> Future:
        """Dispatch one request through the first live member willing
        to take it. A member that dies AFTER accepting (killed
        mid-soak: its Future fails with a router-level error) is
        failed over too — the accepted request re-dispatches through
        the next member, which is the zero-lost-requests contract.
        Raises :class:`AllRoutersUnavailableError` only when every
        member is router-level dead."""
        if self._closed:
            raise RuntimeError("RouterGroup is closed")
        n = len(self._routers)
        if session is not None:
            start = session_key(session) % n
        else:
            with self._lock:
                start = self._rr % n
                self._rr += 1
        caller: Future = Future()
        self._dispatch(payload, timeout_s, session, caller, start, 0,
                       None)
        return caller

    def _dispatch(self, payload: Any, timeout_s: "float | None",
                  session: Any, caller: Future, start: int, k0: int,
                  last: "BaseException | None") -> None:
        """Walk members from group offset ``k0`` until one accepts,
        chaining its Future into ``caller``. Raises when none can —
        the sync leg (``submit``) lets that propagate; the async
        failover leg catches it onto ``caller``."""
        n = len(self._routers)
        for k in range(k0, n):
            idx = (start + k) % n
            router = self._routers[idx]
            if getattr(router, "closed", False):
                continue
            try:
                fut = router.submit(payload, timeout_s=timeout_s,
                                    session=session)
            except (QueueFullError, AllHostsUnavailableError):
                # the FLEET's verdict, not this router's: every live
                # member routes over the same hosts
                raise
            except _ROUTER_LEVEL_ERRORS as e:
                last = e
                continue
            except RuntimeError as e:
                if getattr(router, "closed", False):
                    # closed between the check and the call (the
                    # kill-mid-soak race): this member is gone, walk on
                    last = e
                    continue
                raise
            _M_DISPATCH.inc(router=self._name(idx))
            fut.add_done_callback(
                lambda f, k=k: self._on_result(
                    f, payload, timeout_s, session, caller, start, k))
            return
        raise AllRoutersUnavailableError(
            f"none of the {n} routers can dispatch"
            + (f" (last: {type(last).__name__}: {last})" if last else ""))

    def _on_result(self, fut: Future, payload: Any,
                   timeout_s: "float | None", session: Any,
                   caller: Future, start: int, k: int) -> None:
        if fut.cancelled():
            caller.cancel()
            return
        exc = fut.exception()
        if exc is None:
            try:
                caller.set_result(fut.result())
            except InvalidStateError:
                pass  # the caller cancelled; the result is dropped
            return
        if isinstance(exc, _ROUTER_LEVEL_ERRORS):
            # the ROUTER died holding the request (kill-mid-soak): the
            # accepted request walks on to the next member — zero lost
            _M_ROUTER_FAILOVERS.inc()
            flight.record_event(
                "fabric.router_failover",
                router=self._name((start + k) % len(self._routers)))
            try:
                self._dispatch(payload, timeout_s, session, caller,
                               start, k + 1, exc)
                return
            except Exception as e:
                exc = e
        try:
            caller.set_exception(exc)
        except InvalidStateError:
            pass

    # -- maintenance ---------------------------------------------------------
    def refresh(self) -> None:
        """Refresh every live member's fleet view (tests drive this
        manually; production members run their own refresh threads)."""
        for r in self.live_routers():
            r.refresh()

    def snapshot(self) -> "dict[str, Any]":
        members = []
        for i, r in enumerate(self._routers):
            closed = getattr(r, "closed", False)
            entry: "dict[str, Any]" = {
                "router": self._name(i), "closed": closed}
            if not closed:
                try:
                    entry.update(r.snapshot())
                except Exception as e:
                    entry["error"] = type(e).__name__
            members.append(entry)
        live = sum(not m["closed"] for m in members)
        return {"routers": len(members), "live": live,
                "members": members}

    def close(self, *, close_members: bool = False) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if close_members:
            for r in self._routers:
                try:
                    r.close()
                except Exception:  # pragma: no cover - shutdown guard
                    pass
        self._g_routers.set(0)
        flight.record_event("fabric.router_group_close")

    def __enter__(self) -> "RouterGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- HTTP front (one router per process, PR 14's transport) -------------------
class _RouterHandler(BaseHTTPRequestHandler):
    server_owner: "RouterServer"  # set on the per-instance subclass

    def _reply(self, status: int, body: dict) -> None:
        data = json.dumps(body, default=repr).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            if path == "/router/snapshot":
                self._reply(200, self.server_owner.router.snapshot())
            else:
                self.send_error(404)
        except Exception as e:  # transport must answer, never hang
            name, status = _status_for(e)
            self._reply(status, {"error": name, "message": str(e)})

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": "ValueError", "message": str(e)})
            return
        try:
            if path == "/router/submit":
                self._reply(200, self.server_owner.handle_submit(body))
            else:
                self.send_error(404)
        except Exception as e:
            name, status = _status_for(e)
            self._reply(status, {"error": name, "message": str(e)})

    def log_message(self, fmt, *args):  # no stdout spam per request
        _log.debug("fabric-router: " + fmt, *args)


class RouterServer:
    """Serve one :class:`Router` over HTTP — the process form of a
    router-tier member. ``POST /router/submit`` blocks for the
    generation (same thin-transport trade as the host tier);
    ``GET /router/snapshot`` is the operator view."""

    def __init__(self, router: Router, *, port: int = 0, host: str = "",
                 result_timeout_s: float = 120.0):
        self.router = router
        self.result_timeout_s = result_timeout_s
        handler = type("_BoundRouterHandler", (_RouterHandler,),
                       {"server_owner": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="sparkdl-fabric-router-http", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def handle_submit(self, body: dict) -> dict:
        timeout_s = body.get("timeout_s")
        timeout = float(timeout_s) if timeout_s is not None else None
        payload = {"prompt": np.asarray(body["prompt"], np.int32),
                   "max_new_tokens": int(body["max_new_tokens"])}
        fut = self.router.submit(payload, timeout_s=timeout,
                                 session=body.get("session"))
        result = fut.result(timeout=self.result_timeout_s)
        return {"tokens": [int(t) for t in np.asarray(result).ravel()]}

    def close(self, *, close_router: bool = False) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
        if close_router:
            self.router.close()

    def __enter__(self) -> "RouterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RouterHandle:
    """Client side of :class:`RouterServer`, shaped like a router for
    :class:`RouterGroup` membership: ``submit`` returns a Future backed
    by a small thread pool, transport death raises
    :class:`HostUnavailableError` (a router-level error — the group
    walks on), and ``closed`` turns True once the remote stops
    answering so the group stops offering it work."""

    def __init__(self, base_url: str, *, max_inflight: int = 32,
                 connect_timeout_s: float = 10.0,
                 result_timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.connect_timeout_s = connect_timeout_s
        self.result_timeout_s = result_timeout_s
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=max_inflight,
            thread_name_prefix="sparkdl-fabric-router-client")

    @property
    def closed(self) -> bool:
        return self._closed

    def _request(self, path: str, body: "dict | None" = None,
                 timeout_s: "float | None" = None) -> dict:
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"},
            method="POST" if body is not None else "GET")
        try:
            with urllib.request.urlopen(
                    req, timeout=(timeout_s if timeout_s is not None
                                  else self.connect_timeout_s)) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except (ValueError, json.JSONDecodeError):
                payload = {}
            _raise_remote(payload.get("error"),
                          payload.get("message", str(e)))
        except urllib.error.URLError as e:
            # the remote router process is gone: mark this member dead
            # so the group skips it without a connect round-trip
            self._closed = True
            raise HostUnavailableError(
                f"router unreachable at {url}: {e.reason}") from e

    def submit(self, payload: Any, *, timeout_s: "float | None" = None,
               session: Any = None) -> Future:
        if self._closed:
            raise RuntimeError("RouterHandle is closed")
        body = {
            "prompt": [int(t) for t in payload["prompt"]],
            "max_new_tokens": int(payload["max_new_tokens"]),
            "timeout_s": timeout_s,
        }
        if session is not None:
            body["session"] = session

        # a dead remote fails the Future with HostUnavailableError —
        # the group's ASYNC failover leg re-dispatches the request
        def call():
            out = self._request(
                "/router/submit", body,
                timeout_s=((timeout_s if timeout_s is not None
                            else self.result_timeout_s)
                           + self.connect_timeout_s))
            return np.asarray(out["tokens"], np.int32)

        return self._pool.submit(call)

    def snapshot(self) -> "dict[str, Any]":
        return self._request("/router/snapshot")

    def refresh(self) -> None:
        """Remote members refresh on their own thread; nothing to do."""

    def close(self) -> None:
        self._closed = True
        self._pool.shutdown(wait=False)
