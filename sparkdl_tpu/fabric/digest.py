"""Prefix→host digests: the state that makes routing cache-aware.

The PR 10 radix prefix cache made placement a *performance* decision:
the host that already holds a prompt's prefix blocks prefills 2.2-2.5x
cheaper than a cold one (PERF.md), so a router that knows *where the
blocks live* beats any load balancer on shared-prefix traffic. Shipping
the tries themselves would be absurd; instead each host publishes a
**digest** — the chained :func:`~sparkdl_tpu.serving.prefix_cache.chain_hash`
values of its cached block-aligned prompt prefixes, most-recently-used
first, bounded (``PrefixCache.block_hashes``). The router hashes an
incoming prompt's own block-aligned prefixes ONCE
(:func:`prompt_block_hashes`, O(L) via the same hash chain) and counts
the longest consecutive run present in each host's digest
(:func:`match_blocks`): that count *is* the affinity signal, in blocks.

Digests are advisory, never authoritative: a stale entry (the host
evicted the blocks since publishing) costs one cold prefill on the
"wrong" host — exactly what a digest-less router would have paid —
never a failure. That is why staleness degrades to plain load routing
instead of needing consistency machinery (tested in
tests/fabric/test_fabric_digest.py).
"""

from __future__ import annotations

import dataclasses
import time

from sparkdl_tpu.serving.prefix_cache import DIGEST_ROOT, chain_hash

__all__ = [
    "HostDigest",
    "match_blocks",
    "prompt_block_hashes",
]


def prompt_block_hashes(tokens, block_size: int,
                        max_blocks: int = 64) -> "list[int]":
    """Chained hashes of ``tokens``' block-aligned prefixes: entry ``i``
    covers tokens ``[0, (i+1)*block_size)``. The LAST prompt token never
    participates (the cache holds K/V, not logits — the same
    ``tokens[:-1]`` rule ``PrefixCache.match`` applies), so the deepest
    hash covers at most ``len(tokens) - 1`` tokens. ``max_blocks``
    bounds router-side work on very long prompts; affinity past 64
    blocks adds nothing a scheduler can act on."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    usable = len(tokens) - 1  # the final token always prefills
    out: "list[int]" = []
    h = DIGEST_ROOT
    for i in range(min(usable // block_size, max_blocks)):
        h = chain_hash(
            h, tuple(int(t)
                     for t in tokens[i * block_size:(i + 1) * block_size]))
        out.append(h)
    return out


@dataclasses.dataclass
class HostDigest:
    """One host's published prefix digest, as the router holds it.

    ``hashes`` is the membership set; ``version`` is the host's own
    monotonic publish counter (debugging/telemetry — the router always
    replaces wholesale on refresh); ``fetched_at`` stamps staleness."""

    host_id: str
    block_size: int
    hashes: frozenset
    version: int = 0
    fetched_at: float = dataclasses.field(default_factory=time.monotonic)

    @classmethod
    def from_snapshot(cls, snap: "dict | None") -> "HostDigest | None":
        """Build from the dict form ``engine.prefix_digest()`` /
        ``GET /fabric/digest`` returns (None passes through: a dense
        host publishes no digest)."""
        if not snap:
            return None
        return cls(
            host_id=str(snap["host_id"]),
            block_size=int(snap["block_size"]),
            hashes=frozenset(int(h) for h in snap["hashes"]),
            version=int(snap.get("version") or 0),
        )

    def age_s(self, now: "float | None" = None) -> float:
        return (now if now is not None else time.monotonic()) \
            - self.fetched_at


def match_blocks(prompt_hashes: "list[int]",
                 digest: "HostDigest | None") -> int:
    """Longest CONSECUTIVE run of ``prompt_hashes`` (from the start)
    present in ``digest`` — the estimated cached-prefix depth, in
    blocks. Consecutive-from-zero mirrors what the radix match can
    actually reuse: a hole at block ``i`` makes every deeper block
    unreachable. 0 for hosts without a digest."""
    if digest is None:
        return 0
    n = 0
    for h in prompt_hashes:
        if h not in digest.hashes:
            break
        n += 1
    return n
