"""Prefix→host digests: the state that makes routing cache-aware.

The PR 10 radix prefix cache made placement a *performance* decision:
the host that already holds a prompt's prefix blocks prefills 2.2-2.5x
cheaper than a cold one (PERF.md), so a router that knows *where the
blocks live* beats any load balancer on shared-prefix traffic. Shipping
the tries themselves would be absurd; instead each host publishes a
**digest** — the chained :func:`~sparkdl_tpu.serving.prefix_cache.chain_hash`
values of its cached block-aligned prompt prefixes, most-recently-used
first, bounded (``PrefixCache.block_hashes``). The router hashes an
incoming prompt's own block-aligned prefixes ONCE
(:func:`prompt_block_hashes`, O(L) via the same hash chain) and counts
the longest consecutive run present in each host's digest
(:func:`match_blocks`): that count *is* the affinity signal, in blocks.

Digests are advisory, never authoritative: a stale entry (the host
evicted the blocks since publishing) costs one cold prefill on the
"wrong" host — exactly what a digest-less router would have paid —
never a failure. That is why staleness degrades to plain load routing
instead of needing consistency machinery (tested in
tests/fabric/test_fabric_digest.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time

from sparkdl_tpu.serving.prefix_cache import DIGEST_ROOT, chain_hash

__all__ = [
    "HostDigest",
    "hrw_preferred_host",
    "hrw_score",
    "match_blocks",
    "path_anchor",
    "placement_key",
    "prompt_block_hashes",
    "session_key",
]


# -- rendezvous (HRW) placement ------------------------------------------------
# Every router must map the same key to the same host with NO shared
# state (ROADMAP item 2). Rendezvous hashing gives that for free: score
# every (key, host) pair with a seedless hash and take the max — hosts
# agree everywhere, and removing one host only remaps the keys that
# scored highest on it (1/N churn, vs a modulo ring's near-total
# reshuffle). blake2b keeps it PYTHONHASHSEED-independent like the
# digest chain itself.

def hrw_score(key: int, host_id: str) -> int:
    """Rendezvous weight of ``host_id`` for 64-bit ``key``."""
    return int.from_bytes(
        hashlib.blake2b(
            int(key).to_bytes(8, "little", signed=False)
            + host_id.encode("utf-8"),
            digest_size=8).digest(),
        "little")


def hrw_preferred_host(key: int, host_ids) -> "str | None":
    """The fleet-wide agreed host for ``key``: max rendezvous score,
    host_id as the total-order tie-break (scores collide only by hash
    accident; the lexicographic fallback keeps even that deterministic).
    None for an empty candidate set."""
    best = None
    for hid in host_ids:
        cand = (hrw_score(key, hid), hid)
        if best is None or cand > best:
            best = cand
    return best[1] if best is not None else None


def placement_key(tokens, block_size: int) -> int:
    """The 64-bit key routers hash a prompt under. The FIRST block's
    chain hash when the prompt fills one (so every continuation of a
    conversation — whose prefixes share that block — lands on the same
    preferred host), else the chain hash of the whole usable prompt
    (short prompts have no shared-prefix structure to exploit; any
    stable key spreads them)."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    usable = len(tokens) - 1  # the final token always prefills
    if usable >= block_size:
        toks = tuple(int(t) for t in tokens[:block_size])
    else:
        toks = tuple(int(t) for t in tokens)
    return chain_hash(DIGEST_ROOT, toks)


def path_anchor(tokens, block_size: int) -> int:
    """First-block chain hash of a FULL block-aligned path (migration
    uses this to pick a parked session's new home). Unlike
    :func:`placement_key` there is no trailing-token discount: the
    tokens ARE the cached path. Equal to the placement_key of any
    longer next-turn prompt extending the same conversation, which is
    exactly why migrated sessions land where their next turn routes."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    return chain_hash(DIGEST_ROOT, tuple(int(t) for t in tokens[:block_size]))


def session_key(session) -> int:
    """Deterministic 64-bit key for a sticky-session id — the salt that
    keeps session placement independent of prompt placement. Survives
    router restarts and LRU pressure because it is pure arithmetic on
    the id the client already resends every turn."""
    return int.from_bytes(
        hashlib.blake2b(
            b"sparkdl-session:" + str(session).encode("utf-8"),
            digest_size=8).digest(),
        "little")


def prompt_block_hashes(tokens, block_size: int,
                        max_blocks: int = 64) -> "list[int]":
    """Chained hashes of ``tokens``' block-aligned prefixes: entry ``i``
    covers tokens ``[0, (i+1)*block_size)``. The LAST prompt token never
    participates (the cache holds K/V, not logits — the same
    ``tokens[:-1]`` rule ``PrefixCache.match`` applies), so the deepest
    hash covers at most ``len(tokens) - 1`` tokens. ``max_blocks``
    bounds router-side work on very long prompts; affinity past 64
    blocks adds nothing a scheduler can act on."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    usable = len(tokens) - 1  # the final token always prefills
    out: "list[int]" = []
    h = DIGEST_ROOT
    for i in range(min(usable // block_size, max_blocks)):
        h = chain_hash(
            h, tuple(int(t)
                     for t in tokens[i * block_size:(i + 1) * block_size]))
        out.append(h)
    return out


@dataclasses.dataclass
class HostDigest:
    """One host's published prefix digest, as the router holds it.

    ``hashes`` is the membership set; ``version`` is the host's own
    monotonic publish counter (debugging/telemetry — the router always
    replaces wholesale on refresh); ``fetched_at`` stamps staleness."""

    host_id: str
    block_size: int
    hashes: frozenset
    version: int = 0
    fetched_at: float = dataclasses.field(default_factory=time.monotonic)

    @classmethod
    def from_snapshot(cls, snap: "dict | None") -> "HostDigest | None":
        """Build from the dict form ``engine.prefix_digest()`` /
        ``GET /fabric/digest`` returns (None passes through: a dense
        host publishes no digest)."""
        if not snap:
            return None
        return cls(
            host_id=str(snap["host_id"]),
            block_size=int(snap["block_size"]),
            hashes=frozenset(int(h) for h in snap["hashes"]),
            version=int(snap.get("version") or 0),
        )

    def apply_delta(self, delta: "dict | None") -> "HostDigest | None":
        """Fold a ``prefix_digest_delta`` payload into this snapshot,
        returning the advanced copy — the ≤KBs/sec path that replaces
        wholesale refresh at steady state (ISSUE 19). Three honest
        outcomes, all safe because digests are advisory:

        * advanced copy — contiguous delta (``since == version``);
        * ``self`` unchanged — stale replay (``version`` ≤ ours): the
          journal re-sent history we already hold, applying it twice
          would double-remove, skipping it is idempotent;
        * ``None`` — gap (the host's journal rolled past us, or its
          block grid changed): the caller falls back to one wholesale
          refresh, exactly what it did every cycle before deltas.
        """
        if not delta:
            return None
        version = int(delta.get("version") or 0)
        if int(delta.get("since") or -1) != self.version:
            return self if version <= self.version else None
        if int(delta.get("block_size") or 0) != self.block_size:
            return None
        added = frozenset(int(h) for h in delta.get("added") or ())
        removed = frozenset(int(h) for h in delta.get("removed") or ())
        return dataclasses.replace(
            self, hashes=(self.hashes - removed) | added,
            version=version, fetched_at=time.monotonic())

    def age_s(self, now: "float | None" = None) -> float:
        return (now if now is not None else time.monotonic()) \
            - self.fetched_at


def match_blocks(prompt_hashes: "list[int]",
                 digest: "HostDigest | None") -> int:
    """Longest CONSECUTIVE run of ``prompt_hashes`` (from the start)
    present in ``digest`` — the estimated cached-prefix depth, in
    blocks. Consecutive-from-zero mirrors what the radix match can
    actually reuse: a hole at block ``i`` makes every deeper block
    unreachable. 0 for hosts without a digest."""
    if digest is None:
        return 0
    n = 0
    for h in prompt_hashes:
        if h not in digest.hashes:
            break
        n += 1
    return n
