"""Host abstraction: one serving host behind a uniform fabric surface.

A :class:`HostHandle` is what the :class:`~sparkdl_tpu.fabric.router.Router`
routes over — the coordinator/worker split of distributed TensorFlow
(Abadi et al., arXiv 1603.04467) applied to the serving tier: the router
is the coordinator, each handle fronts one worker host running its own
engine, and the surface between them is deliberately small:

``submit(payload, timeout_s) -> Future``, ``snapshot()``, ``health()``,
``prefix_digest()``, ``drain()``, ``close()``.

Two implementations:

* :class:`InProcessHost` — wraps a live
  :class:`~sparkdl_tpu.serving.continuous.ContinuousGPTEngine` or
  :class:`~sparkdl_tpu.serving.engine.ServingEngine` in THIS process.
  What tests, the CPU harness, and bench_serving's ``BENCH_HOSTS``
  section use: N real engines, N real prefix caches, zero transport.
* :class:`~sparkdl_tpu.fabric.http.HttpHostHandle` — the thin
  HTTP/json transport over :class:`~sparkdl_tpu.fabric.http.HostServer`
  (the same stdlib-http machinery as the metrics exporter) for real
  multi-process deployments.

Error classes: :data:`HOST_LEVEL_ERRORS` is the *retry class* for
host-level failures — errors that indict the HOST, not the request
(engine shut down, transport dead, host draining), which the router
answers by re-routing the request to a surviving host. Anything else
(deadline exceeded, a bad prompt, a model error) is the request's own
outcome and passes through to the caller exactly once.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any

from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.serving.queue import EngineClosedError, Request

__all__ = [
    "HOST_LEVEL_ERRORS",
    "HostDrainingError",
    "HostHandle",
    "HostUnavailableError",
    "InProcessHost",
]


class HostUnavailableError(RuntimeError):
    """The host cannot take work right now: transport dead, process
    gone, or the handle's circuit is open. Routes re-route on it."""


class HostDrainingError(RuntimeError):
    """The host is draining for a rolling restart: admission stopped,
    in-flight work finishing. A planned state — the router re-routes
    without counting a host failure."""


#: The host-level retry class (ISSUE 14): a Future failing with one of
#: these means the HOST lost the request, not that the request failed —
#: the router re-submits it to a surviving host. ConnectionError/OSError
#: cover the HTTP transport (urllib's URLError subclasses OSError).
HOST_LEVEL_ERRORS = (
    HostUnavailableError,
    HostDrainingError,
    EngineClosedError,
    ConnectionError,
    OSError,
)


class HostHandle:
    """The surface a fabric host exposes to the router (see module
    docstring). Subclass and implement; ``host_id`` must be stable for
    the handle's lifetime."""

    host_id: str

    def submit(self, payload: "dict[str, Any]", *,
               timeout_s: "float | None" = None) -> Future:
        raise NotImplementedError

    def snapshot(self) -> "dict[str, Any]":
        raise NotImplementedError

    def capacity(self) -> "dict[str, Any]":
        raise NotImplementedError

    def health(self) -> "dict[str, Any]":
        raise NotImplementedError

    def prefix_digest(self, max_entries: int = 1024) -> "dict | None":
        raise NotImplementedError

    def prefix_digest_delta(self, since_version: int,
                            max_entries: int = 1024) -> "dict | None":
        """Journal of block-hash adds/removes since ``since_version``
        (ISSUE 19), or None when the host cannot produce one (no
        journal, gap, dense layout) — the router then re-syncs with one
        wholesale :meth:`prefix_digest`. Defaulting to None keeps every
        pre-delta handle (and test fake) correct: they simply stay on
        the wholesale path."""
        return None

    def export_parked_sessions(self) -> "dict | None":
        """Serialize this host's parked sessions for migration
        (ISSUE 19); None when the host has nothing to export or no
        tier store. Default None: migration quietly no-ops on hosts
        that cannot ship state, and those sessions re-prefill."""
        return None

    def import_parked_sessions(self, bundle: "dict | None") -> int:
        """Adopt migrated parked sessions; returns sessions adopted.
        Default 0: a host that cannot import simply lets the sessions
        re-prefill — the pre-migration cost, never an error."""
        return 0

    def trace(self, request_id: int) -> "dict[str, Any]":
        """This host's span fragments for one trace (ISSUE 17):
        ``{"host_id", "now_us", "spans"}``. ``now_us`` is the host's
        trace clock (µs since its process epoch) read while serving the
        call — the fleet scraper pairs it with the RPC round-trip
        midpoint to estimate this host's clock offset, so fragments
        from hosts with unrelated monotonic epochs stitch into one
        skew-corrected timeline."""
        raise NotImplementedError

    def drain(self) -> "list[Request]":
        """Stop admission; return the unstarted requests (in-process
        handles return live :class:`Request` objects for queue-level
        transfer; transports return [] and fail their blocked submits
        with :class:`HostDrainingError` so the router's failover path
        re-places them)."""
        raise NotImplementedError

    def close(self, *, timeout_s: "float | None" = 30.0) -> None:
        raise NotImplementedError


class InProcessHost(HostHandle):
    """A fabric host over an engine living in this process.

    ``payload`` for a :class:`ContinuousGPTEngine` host is
    ``{"prompt": <1-D int ids>, "max_new_tokens": n}``; for a
    :class:`ServingEngine` host it is whatever that engine's extract
    eats (the router treats it opaquely either way — only the GPT
    payload's ``prompt`` feeds affinity scoring).
    """

    def __init__(self, engine: Any, *, host_id: "str | None" = None):
        self.engine = engine
        self.host_id = (host_id if host_id is not None
                        else str(getattr(engine, "host_id", id(engine))))
        #: GPT engines take (prompt, max_new_tokens); micro-batching
        #: engines take the payload whole
        self._gpt = hasattr(engine, "kv_layout")
        self._drained = threading.Event()

    def submit(self, payload: "dict[str, Any]", *,
               timeout_s: "float | None" = None) -> Future:
        fault_point("host.submit")
        if self._drained.is_set():
            raise HostDrainingError(
                f"host {self.host_id} is draining; route elsewhere")
        if isinstance(payload, dict) and "handoff" in payload:
            # cross-tier KV handoff (ISSUE 16): the decode-tier
            # admission path — installed blocks, no re-prefill
            return self.engine.submit_handoff(
                payload["handoff"], timeout_s=timeout_s)
        if self._gpt:
            # tenant/priority ride the payload only when the submitter
            # set them (ISSUE 20): an absent key leaves the engine's
            # defaults untouched — the bitwise single-user path
            extra = {k: payload[k] for k in ("tenant", "priority")
                     if payload.get(k) is not None}
            return self.engine.submit(
                payload["prompt"], payload["max_new_tokens"],
                timeout_s=timeout_s, **extra)
        return self.engine.submit(payload, timeout_s=timeout_s)

    def snapshot(self) -> "dict[str, Any]":
        return self.engine.snapshot()

    def capacity(self) -> "dict[str, Any]":
        return self.engine.capacity()

    def health(self) -> "dict[str, Any]":
        """Host-local health, shaped like one host's slice of
        ``healthz_report()``: ``unhealthy`` when the engine loop died or
        every replica is quarantined, ``degraded`` on a KV exhaustion
        streak, else ``ok``. (The process-wide ``/healthz`` aggregates
        across every engine in the process, which is the wrong grain
        when several in-process hosts share one process — tests do.)"""
        status = "ok"
        snap = self.engine.snapshot()
        kv = snap.get("kv") or {}
        if kv.get("exhausted_streak"):
            status = "degraded"
        total = snap.get("replica_count")
        healthy = snap.get("healthy_count")
        if healthy == 0 and total:
            status = "unhealthy"
        thread = getattr(self.engine, "_thread", None)
        if (thread is not None and not thread.is_alive()
                and not self.engine.queue.closed):
            # the loop crashed (close() would have closed the queue):
            # this host serves nothing until restarted
            status = "unhealthy"
        return {"status": status, "host_id": self.host_id,
                "draining": self._drained.is_set()}

    def prefix_digest(self, max_entries: int = 1024) -> "dict | None":
        fn = getattr(self.engine, "prefix_digest", None)
        return fn(max_entries) if callable(fn) else None

    def prefix_digest_delta(self, since_version: int,
                            max_entries: int = 1024) -> "dict | None":
        fn = getattr(self.engine, "prefix_digest_delta", None)
        return (fn(since_version, max_entries) if callable(fn)
                else None)

    def export_parked_sessions(self) -> "dict | None":
        fn = getattr(self.engine, "export_parked_sessions", None)
        return fn() if callable(fn) else None

    def import_parked_sessions(self, bundle: "dict | None") -> int:
        fn = getattr(self.engine, "import_parked_sessions", None)
        return int(fn(bundle)) if callable(fn) else 0

    def trace(self, request_id: int) -> "dict[str, Any]":
        from sparkdl_tpu.observability import tracing
        fn = getattr(self.engine, "trace", None)
        spans = (fn(int(request_id)) if callable(fn)
                 else tracing.spans_for_trace(int(request_id)))
        return {"host_id": self.host_id,
                "now_us": tracing.trace_clock_us(),
                "spans": spans}

    def drain(self) -> "list[Request]":
        fault_point("host.drain")
        self._drained.set()
        return self.engine.begin_drain()

    @property
    def draining(self) -> bool:
        return self._drained.is_set()

    def reopen(self) -> None:
        """Reverse :meth:`drain` (ISSUE 16): a drained handle parked on
        ``AutoScaler.spare_hosts`` re-enters service — the engine's
        queue reopens (and its loop restarts if it exited on graceful
        drain) before the handle rejoins a ``Router.add_host``."""
        fn = getattr(self.engine, "reopen", None)
        if callable(fn):
            fn()
        else:
            self.engine.queue.reopen()
        self._drained.clear()

    def requeue(self, requests: "list[Request]") -> None:
        """Adopt requests extracted from ANOTHER host's queue (the
        drain hand-off): queue-level transfer, Futures and trace ids
        intact — see ``RequestQueue.requeue``."""
        self.engine.queue.requeue(requests)

    def close(self, *, timeout_s: "float | None" = 30.0) -> None:
        self.engine.close(drain=True, timeout_s=timeout_s)
