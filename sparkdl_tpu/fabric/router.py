"""Cache-aware router tier over per-host serving engines (ISSUE 14).

ReplicaPool scales across the chips of ONE host; this router is the
front door over MANY hosts (ROADMAP item 1, the coordinator/worker
split of distributed TensorFlow — arXiv 1603.04467, 1603.02339 —
applied to serving). Every placement decision folds three signals:

* **Load** — weighted least-outstanding-work: the router tracks its own
  in-flight count per host and divides by the host's capacity weight
  (``replica_count × n_slots`` from the engine's ``capacity()``
  structure), so a 4-replica host legitimately absorbs 4x a 1-replica
  host's depth before looking equally busy.
* **Affinity** — the PR 10 prefix cache made placement *stateful*: the
  host already holding a prompt's prefix blocks prefills 2.2-2.5x
  cheaper. Hosts publish bounded prefix→host digests
  (:mod:`~sparkdl_tpu.fabric.digest`); the score adds
  ``affinity_weight × min(matched_blocks, affinity_cap_blocks)`` —
  the **cap is the anti-hotspot trade**: past ``affinity_cap_blocks``
  of cached prefix, more affinity buys nothing, so a single hot prefix
  cannot out-bid an arbitrarily large load imbalance and pile the
  whole fleet's traffic on one host. Sticky **sessions** (bounded LRU
  ``session → host`` map) keep a conversation on the host whose cache
  holds its history without re-scoring every turn.
* **Health** — a host answering ``unhealthy`` (its ``/healthz``-shaped
  ``health()``), or failing ``max_failures`` consecutive submissions,
  is quarantined behind the same probation circuit breaker ReplicaPool
  uses: after ``probation_s`` ONE live request probes it (the rider
  protected by the failover re-route), success rejoins, failure doubles
  the backoff up to ``probation_max_s``.

**Spillover admission control**: a host past its saturation bound
(``max_queue_depth + n_slots`` from its capacity, or the explicit
``max_outstanding``) is skipped even when affinity prefers it — the
request lands on the best host WITH room (``sparkdl_fabric_spillover_total``)
— and only an all-saturated fleet rejects (``QueueFullError``), the
same reject-with-error backpressure the single-host queue applies.

**Drain** (rolling restarts): :meth:`drain_host` stops new placements,
extracts the host's accepted-but-unstarted requests, and re-queues them
onto surviving hosts — in-process hosts transfer the live
:class:`~sparkdl_tpu.serving.queue.Request` objects queue-to-queue
(trace ids, deadlines, Futures intact; ``RequestQueue.requeue``),
HTTP hosts fail their blocked submits with
:class:`~sparkdl_tpu.fabric.host.HostDrainingError` and the failover
path re-places them. In-flight requests finish on the draining host.

**Failover**: a Future that fails with a *host-level* error
(:data:`~sparkdl_tpu.fabric.host.HOST_LEVEL_ERRORS` — engine shut
down, transport dead, draining) is re-submitted to a surviving host up
to ``max_failovers`` times before the error reaches the caller; every
hop lands in ``sparkdl_retries_total{site="host.submit"}`` and the
flight ring, and a host quarantine triggers a postmortem bundle whose
router context captures the failover sequence.

Fault sites: ``router.route`` (every placement decision),
``host.submit`` / ``host.drain`` (on the handles).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from concurrent.futures import CancelledError, Future, InvalidStateError
from typing import Any, Iterable

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.reliability.breaker import ProbationBreaker
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.reliability.retry import record_retry
from sparkdl_tpu.serving.queue import QueueFullError, Request

from sparkdl_tpu.fabric.digest import (
    HostDigest,
    hrw_preferred_host,
    hrw_score,
    match_blocks,
    path_anchor,
    placement_key,
    prompt_block_hashes,
    session_key,
)
from sparkdl_tpu.fabric.host import (
    HOST_LEVEL_ERRORS,
    HostDrainingError,
    HostHandle,
)

__all__ = ["AllHostsUnavailableError", "Router"]

_M_ROUTED = registry().counter(
    "sparkdl_fabric_routed_total",
    "requests the router placed, by receiving host",
    labels=("host",))
_M_SPILLOVER = registry().counter(
    "sparkdl_fabric_spillover_total",
    "placements diverted off a saturated preferred host, by the host "
    "that absorbed them",
    labels=("host",))
_M_AFFINITY = registry().counter(
    "sparkdl_fabric_affinity_hits_total",
    "placements that landed on a host whose prefix digest matched the "
    "prompt (cache-affine routing wins)",
    labels=("host",))
_M_REQUEUED = registry().counter(
    "sparkdl_fabric_requeued_total",
    "accepted requests re-queued off a draining or failed host onto a "
    "surviving host")
_M_FAILOVERS = registry().counter(
    "sparkdl_fabric_failovers_total",
    "requests re-submitted to another host after a host-level failure")
_M_HOST_QUARANTINED = registry().counter(
    "sparkdl_fabric_host_quarantined_total",
    "hosts quarantined by the router's circuit breaker")
_M_DIGEST_BLOCKS = registry().gauge(
    "sparkdl_fabric_digest_blocks",
    "prefix-digest entries the router holds per host",
    labels=("host",))
_M_DELTA_BYTES = registry().counter(
    "sparkdl_fabric_digest_delta_bytes_total",
    "wire bytes of digest DELTA payloads the router consumed (the "
    "steady-state refresh cost; compare sparkdl_fabric_digest_"
    "wholesale_bytes_total)")
_M_WHOLESALE_BYTES = registry().counter(
    "sparkdl_fabric_digest_wholesale_bytes_total",
    "wire bytes of WHOLESALE digest snapshots the router pulled "
    "(first contact, delta gaps, and hosts without a journal)")
_M_DELTA_APPLIED = registry().counter(
    "sparkdl_fabric_digest_delta_applied_total",
    "digest delta consumption outcomes (applied: folded in; replayed: "
    "stale duplicate skipped idempotently; gap: journal rolled past "
    "this router, wholesale re-sync; error: torn delta fetch, "
    "wholesale re-sync)",
    labels=("outcome",))


class AllHostsUnavailableError(RuntimeError):
    """Every fabric host is quarantined, draining, or unhealthy and
    none is due a probation probe; the fabric cannot place work."""


class _Placement:
    """One routed request's record: what the failover path needs to
    re-submit it somewhere else."""

    __slots__ = ("payload", "session", "deadline", "timeout_s",
                 "attempts", "probe")

    def __init__(self, payload, session, timeout_s):
        self.payload = payload
        self.session = session
        self.timeout_s = timeout_s
        self.deadline = (time.monotonic() + timeout_s
                         if timeout_s is not None else None)
        self.attempts = 0
        self.probe = False


class _HostState:
    """Router-side view of one host (all mutable fields under the
    router lock)."""

    __slots__ = ("handle", "host_id", "outstanding", "routed",
                 "breaker", "draining", "health_status", "digest",
                 "weight", "saturation", "free_slots", "kv_free",
                 "kv_total", "kv_cold", "kv_parked_sessions",
                 "overload_level")

    def __init__(self, handle: HostHandle, saturation: "int | None",
                 breaker: ProbationBreaker):
        self.handle = handle
        self.host_id = handle.host_id
        self.outstanding = 0
        self.routed = 0
        #: the shared quarantine/probation state machine (mutated under
        #: the router lock — one implementation with ReplicaPool)
        self.breaker = breaker
        self.draining = False
        self.health_status = "ok"
        self.digest: "HostDigest | None" = None
        self.weight = 1
        self.saturation = saturation if saturation is not None else 256
        #: headroom-policy signals off the host's capacity() (None
        #: until the first refresh, or when the engine has no paged
        #: pool): slot occupancy + KV availability (ISSUE 16)
        self.free_slots: "int | None" = None
        self.kv_free: "int | None" = None
        self.kv_total: "int | None" = None
        #: tiered-KV signals (ROADMAP item 1): refcount-0 cached
        #: blocks that can page out on demand, and sessions already
        #: parked in the host/disk tiers — pressure that is NOT "full"
        self.kv_cold: "int | None" = None
        self.kv_parked_sessions: "int | None" = None
        #: brownout ladder level off capacity() (ISSUE 20): a browned-
        #: out host's headroom is discounted so the fleet routes new
        #: work around local overload while the ladder sheds it
        self.overload_level = 0

    # breaker state read-throughs (tests and snapshots read these; all
    # WRITES go through the breaker's transition verbs)
    @property
    def quarantined(self) -> bool:
        return self.breaker.quarantined

    @property
    def probing(self) -> bool:
        return self.breaker.probing

    @property
    def consecutive_failures(self) -> int:
        return self.breaker.consecutive_failures

    @property
    def probation_until(self) -> float:
        return self.breaker.probation_until

    @property
    def probation_backoff_s(self) -> float:
        return self.breaker.probation_backoff_s


class Router:
    """Route generation requests over :class:`HostHandle` hosts.

    ``submit(payload, timeout_s=, session=)`` returns a Future; payload
    is ``{"prompt": ids, "max_new_tokens": n}`` for GPT hosts (the
    ``prompt`` feeds affinity scoring) or an opaque feature payload for
    micro-batching hosts. ``policy="round_robin"`` disables scoring
    (the bench baseline); health/saturation/drain handling is identical
    in both policies, so the comparison isolates cache-awareness.

    Construct with ``auto_refresh=False`` for deterministic tests and
    call :meth:`refresh` manually; the default refreshes digests,
    capacity, and health every ``refresh_interval_s`` on a daemon
    thread.
    """

    def __init__(self, hosts: "Iterable[HostHandle]", *,
                 policy: str = "affinity",
                 affinity_weight: float = 1.0,
                 load_weight: float = 1.0,
                 affinity_cap_blocks: int = 8,
                 digest_entries: int = 1024,
                 max_failovers: int = 2,
                 max_failures: int = 3,
                 probation_s: "float | None" = 1.0,
                 probation_max_s: float = 30.0,
                 max_outstanding: "int | None" = None,
                 session_capacity: int = 4096,
                 refresh_interval_s: float = 2.0,
                 auto_refresh: bool = True,
                 placement_block_size: "int | None" = None):
        if policy not in ("affinity", "round_robin", "headroom"):
            raise ValueError(
                f"policy must be 'affinity', 'round_robin', or "
                f"'headroom', got {policy!r}")
        if affinity_cap_blocks < 0:
            raise ValueError(
                f"affinity_cap_blocks must be >= 0, got "
                f"{affinity_cap_blocks}")
        if max_failures < 1:
            raise ValueError(
                f"max_failures must be >= 1, got {max_failures}")
        if probation_s is not None and probation_s <= 0:
            raise ValueError(
                f"probation_s must be > 0 or None, got {probation_s}")
        if placement_block_size is not None and placement_block_size < 1:
            raise ValueError(
                f"placement_block_size must be >= 1 or None, got "
                f"{placement_block_size}")
        self.policy = policy
        #: block grid the rendezvous placement key hashes under; None
        #: derives it from the fleet's published digests (min block
        #: size). Pin it when routers must agree before any digest
        #: arrives (cross-process determinism).
        self.placement_block_size = placement_block_size
        self.affinity_weight = affinity_weight
        self.load_weight = load_weight
        self.affinity_cap_blocks = affinity_cap_blocks
        self.digest_entries = digest_entries
        self.max_failovers = max_failovers
        self.max_failures = max_failures
        self.probation_s = probation_s
        self.probation_max_s = probation_max_s
        self.max_outstanding = max_outstanding
        self.session_capacity = session_capacity
        self.refresh_interval_s = refresh_interval_s
        states = [_HostState(h, max_outstanding, self._make_breaker())
                  for h in hosts]
        if not states:
            raise ValueError("a Router needs at least one host")
        ids = [s.host_id for s in states]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids: {sorted(ids)}")
        self._hosts: "dict[str, _HostState]" = {
            s.host_id: s for s in states}
        self._sessions: "collections.OrderedDict[Any, str]" = \
            collections.OrderedDict()
        self._rr = 0
        self._closed = False
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self.refresh()
        # process-wide registrations LAST (the engine-constructor rule):
        # /healthz and postmortem bundles read live fabric state here —
        # the snapshot exposes replica_count/healthy_count in the pool
        # shape healthz_report() aggregates, so an all-hosts-down fabric
        # answers 503 at the front door
        self._flight_name = flight.add_context_provider(
            f"fabric-router-{id(self):x}", self.snapshot)
        flight.record_event(
            "fabric.start", router=self._flight_name, hosts=len(states),
            policy=policy)
        self._refresh_thread: "threading.Thread | None" = None
        if auto_refresh and refresh_interval_s > 0:
            self._refresh_thread = threading.Thread(
                target=self._refresh_worker,
                name="sparkdl-fabric-refresh", daemon=True)
            self._refresh_thread.start()

    def _make_breaker(self) -> ProbationBreaker:
        return ProbationBreaker(
            max_failures=self.max_failures,
            probation_s=self.probation_s,
            probation_max_s=self.probation_max_s,
        )

    # -- submission ----------------------------------------------------------
    def submit(self, payload: Any, *, timeout_s: "float | None" = None,
               session: Any = None) -> Future:
        """Place one request on the best host; returns a Future that
        survives host-level failures up to ``max_failovers`` re-routes.
        Raises :class:`QueueFullError` when every eligible host is
        saturated and :class:`AllHostsUnavailableError` when none is
        eligible at all."""
        if self._closed:
            raise RuntimeError("Router is closed")
        rec = _Placement(payload, session, timeout_s)
        caller: Future = Future()
        self._dispatch(rec, caller, exclude=None)
        return caller

    def _dispatch(self, rec: _Placement, caller: Future,
                  exclude: "_HostState | None") -> None:
        state = self._place(rec, exclude)
        remaining = rec.timeout_s
        if rec.deadline is not None:
            remaining = max(0.001, rec.deadline - time.monotonic())
        try:
            inner = state.handle.submit(rec.payload, timeout_s=remaining)
        except Exception as e:
            reroute = (isinstance(e, QueueFullError)
                       or isinstance(e, HOST_LEVEL_ERRORS))
            with self._lock:
                state.outstanding -= 1
                if rec.probe and not reroute:
                    # same release as the async path: a request-level
                    # reject at the door (bad prompt) says nothing
                    # about the host — free the probe slot
                    state.breaker.release_probe()
            if reroute:
                # the host refused at the door (raced saturation, drain,
                # injected host.submit fault): same failover path as an
                # asynchronous host failure
                self._fail_or_reroute(rec, state, caller, e)
                return
            raise
        inner.add_done_callback(
            lambda f, rec=rec, state=state, caller=caller:
            self._on_result(rec, state, caller, f))

    def _payload_prompt(self, payload: Any):
        if isinstance(payload, dict):
            return payload.get("prompt")
        return getattr(payload, "prompt", None)

    def _place(self, rec: _Placement,
               exclude: "_HostState | None", *,
               transfer: bool = False) -> _HostState:
        """Pick a host and charge it one outstanding unit. Handle calls
        never happen under the router lock (deadlock discipline shared
        with ReplicaPool). ``transfer=True`` is the drain-transfer
        placement: quarantined hosts are out entirely (a transfer
        bypasses the router's completion callbacks, so it can neither
        release a probe slot nor survive landing in a dead host's
        queue) and saturation does NOT reject — the requests were
        already accepted, and the target queue's cross-queue ``requeue``
        absorbs transfers past ``max_depth`` by contract."""
        fault_point("router.route")
        prompt = (self._payload_prompt(rec.payload)
                  if self.policy == "affinity" else None)
        # hash outside the lock (pure CPU work); one digest grid per
        # distinct block size in the fleet (normally exactly one)
        hashes_by_bs: "dict[int, list[int]]" = {}
        pkey: "int | None" = None
        if prompt is not None and len(prompt):
            with self._lock:
                sizes = {s.digest.block_size
                         for s in self._hosts.values()
                         if s.digest is not None}
            hashes_by_bs = {
                bs: prompt_block_hashes(prompt, bs,
                                        self.affinity_cap_blocks)
                for bs in sizes}
            pbs = (self.placement_block_size
                   or (min(sizes) if sizes else 16))
            pkey = placement_key(prompt, pbs)
        spilled = False
        affine = False
        probe = False
        chosen: "_HostState | None" = None
        with self._lock:
            now = time.monotonic()
            candidates = [
                s for s in self._hosts.values()
                if s is not exclude and not s.draining
                and s.health_status not in ("unhealthy", "unreachable")
                and (not s.quarantined
                     or (not transfer and s.breaker.probe_due(now)))
            ]
            if candidates:
                chosen = self._sticky_locked(rec, candidates,
                                             hashes_by_bs)
                if chosen is None:
                    chosen, spilled, affine = self._score_locked(
                        rec, candidates, hashes_by_bs, pkey,
                        include_saturated=transfer)
                if chosen.quarantined:
                    chosen.breaker.begin_probe()
                    probe = True
                chosen.outstanding += 1
                chosen.routed += 1
                if rec.session is not None:
                    self._sessions[rec.session] = chosen.host_id
                    self._sessions.move_to_end(rec.session)
                    while len(self._sessions) > self.session_capacity:
                        self._sessions.popitem(last=False)
        if chosen is None:
            # event + postmortem trigger outside the lock (the dump's
            # providers call snapshot(), which takes it again)
            flight.record_event(
                "fabric.no_hosts", hosts=len(self._hosts))
            flight.trigger_dump("fabric_unavailable")
            raise AllHostsUnavailableError(
                f"none of the {len(self._hosts)} fabric hosts can "
                "take work (quarantined/draining/unhealthy)")
        if probe:
            rec.probe = True
        _M_ROUTED.inc(host=chosen.host_id)
        if spilled:
            _M_SPILLOVER.inc(host=chosen.host_id)
        if affine:
            _M_AFFINITY.inc(host=chosen.host_id)
        return chosen

    def _sticky_locked(self, rec: _Placement,
                       candidates: "list[_HostState]",
                       hashes_by_bs: "dict[int, list[int]]"
                       ) -> "_HostState | None":
        """Place a continuing session on the host that holds its
        history. Three steps, strongest evidence first (ISSUE 19 — the
        per-router LRU alone silently dropped affinity under churn and
        never survived a router restart):

        1. the LRU remembers a still-eligible host with room — the
           fast path, same as always;
        2. no LRU entry, but some host's DIGEST matches the prompt —
           real cache evidence (this router restarted, or another
           router placed the session, or the session migrated): fall
           through to scoring, which follows the match;
        3. neither — rendezvous-hash the session id over the open
           candidates, so every router (and every restart of this one)
           derives the same home without sharing the LRU.
        First placements with no session and failover re-routes fall
        through to scoring."""
        if rec.session is None or rec.attempts:
            return None
        host_id = self._sessions.get(rec.session)
        if host_id is not None:
            for s in candidates:
                if (s.host_id == host_id and not s.quarantined
                        and s.outstanding < s.saturation):
                    return s
            return None
        if hashes_by_bs:
            for s in candidates:
                if s.digest is None:
                    continue
                hashes = hashes_by_bs.get(s.digest.block_size)
                if hashes and match_blocks(hashes, s.digest):
                    return None  # cache evidence beats the hash
        open_hosts = [s for s in candidates if not s.quarantined
                      and s.outstanding < s.saturation]
        if not open_hosts:
            return None
        skey = session_key(rec.session)
        best = max(open_hosts,
                   key=lambda s: (hrw_score(skey, s.host_id), s.host_id))
        return best

    def _score_locked(self, rec: _Placement,
                      candidates: "list[_HostState]",
                      hashes_by_bs: "dict[int, list[int]]",
                      pkey: "int | None" = None,
                      include_saturated: bool = False
                      ) -> "tuple[_HostState, bool, bool]":
        """(chosen, spilled, affine): affinity-bonus minus load-penalty
        over the non-saturated candidates; ``spilled`` reports that a
        saturated host would have scored best (spillover admission
        control diverted the request). ``include_saturated`` (drain
        transfers) scores every candidate — already-accepted traffic is
        never re-rejected. ``pkey`` (the prompt's rendezvous placement
        key) breaks score TIES deterministically so N routers with the
        same view agree — it never outvotes load or affinity, which is
        the whole disagreement-window story: routers whose views differ
        disagree only inside the tie set, costing at most one cold
        prefill, never correctness."""
        def bonus(s: _HostState) -> int:
            if not hashes_by_bs or s.digest is None:
                return 0
            # .get: a refresh may have swapped in a digest with a block
            # size unseen when the prompt was hashed (pre-lock) — worth
            # zero affinity this placement, correct next one
            hashes = hashes_by_bs.get(s.digest.block_size)
            if hashes is None:
                return 0
            hit = match_blocks(hashes, s.digest)
            return min(hit, self.affinity_cap_blocks)

        open_hosts = (list(candidates) if include_saturated
                      else [s for s in candidates
                            if s.outstanding < s.saturation])
        if not open_hosts:
            raise QueueFullError(
                f"all {len(candidates)} eligible fabric hosts are "
                "saturated; retry with backoff or add hosts")
        if self.policy == "round_robin":
            chosen = open_hosts[self._rr % len(open_hosts)]
            self._rr += 1
            return chosen, False, False
        if self.policy == "headroom":
            # decode-tier placement (ISSUE 16): slot headroom discounted
            # by KV availability — a host with free slots but a nearly
            # exhausted block pool would only DEFER the installed
            # request, so it scores like a busy one. The router-side
            # outstanding count keeps the score live between capacity
            # refreshes; the load penalty breaks ties the stale
            # free-slot reading cannot. Cold cached blocks count as
            # available (ROADMAP item 1): a tiered host pages them out
            # on demand, so pressure that is parkable idle sessions
            # must not score the host as full.
            def room(s: _HostState) -> float:
                free = (s.free_slots if s.free_slots is not None
                        else s.weight)
                free = max(0.0, free - s.outstanding)
                kv = 1.0
                if s.kv_total:
                    avail = max(0.0, s.kv_free or 0) + (s.kv_cold or 0)
                    kv = min(1.0, avail / s.kv_total)
                # brownout discount (ISSUE 20): each ladder level halves
                # the advertised room — a browned-out host keeps serving
                # but stops attracting NEW work over healthy peers
                return free * kv / (1 << min(s.overload_level, 4))

            scores = {
                s.host_id: (room(s)
                            - self.load_weight * s.outstanding / s.weight)
                for s in candidates}
            best_score = max(scores[s.host_id] for s in open_hosts)
            ties = [s for s in open_hosts
                    if scores[s.host_id] == best_score]
            chosen = ties[self._rr % len(ties)]
            self._rr += 1
            return chosen, max(scores.values()) > best_score, False
        # score each host exactly once (nothing can change under the
        # held lock): the digest walks are the lock's hot-path cost
        bonuses = {s.host_id: bonus(s) for s in candidates}
        # the brownout penalty mirrors the headroom policy's discount
        # (ISSUE 20): one load_weight unit per ladder level, so a
        # browned-out host loses affinity ties to healthy peers
        scores = {
            s.host_id: (self.affinity_weight * bonuses[s.host_id]
                        - self.load_weight * s.outstanding / s.weight
                        - self.load_weight * s.overload_level)
            for s in candidates}
        best_score = max(scores[s.host_id] for s in open_hosts)
        ties = [s for s in open_hosts if scores[s.host_id] == best_score]
        if pkey is not None:
            # rendezvous tie-break: every router resolves the same tie
            # the same way, with no shared state (ISSUE 19)
            chosen = max(ties, key=lambda s: (hrw_score(pkey, s.host_id),
                                              s.host_id))
        else:
            chosen = ties[self._rr % len(ties)]
            self._rr += 1
        # spillover: a saturated host would have outscored the choice
        spilled = max(scores.values()) > best_score
        return chosen, spilled, bonuses[chosen.host_id] > 0

    # -- completion / failover (runs on host worker threads) -----------------
    @staticmethod
    def _resolve_caller(caller: Future, *, result: Any = None,
                        exc: "BaseException | None" = None) -> None:
        """Resolve the caller-facing Future, tolerating a caller that
        cancelled it while the work was in flight (the router never
        marks it RUNNING, so cancel() can win any time before this; the
        result is simply dropped — the work already happened)."""
        try:
            if exc is not None:
                caller.set_exception(exc)
            else:
                caller.set_result(result)
        except InvalidStateError:
            pass

    def _on_result(self, rec: _Placement, state: _HostState,
                   caller: Future, fut: Future) -> None:
        try:
            self._on_result_inner(rec, state, caller, fut)
        except Exception as e:  # a hung caller Future is worse than
            self._resolve_caller(caller, exc=e)  # any error it carries

    def _on_result_inner(self, rec: _Placement, state: _HostState,
                         caller: Future, fut: Future) -> None:
        exc = (CancelledError("host cancelled the request")
               if fut.cancelled() else fut.exception())
        if exc is None:
            with self._lock:
                state.outstanding -= 1
                rejoined = state.breaker.record_success()
            if rejoined:
                flight.record_event(
                    "fabric.host_reintegrated", host=state.host_id)
            if rec.attempts:
                record_retry("host.submit", "recovered")
            self._resolve_caller(caller, result=fut.result())
            return
        with self._lock:
            state.outstanding -= 1
            if rec.probe and not isinstance(exc, HOST_LEVEL_ERRORS):
                # the probe's request failed for its own reasons
                # (deadline on the recovering host's queue, bad
                # prompt): inconclusive about the HOST — release the
                # probe slot so the next due probe can run, else the
                # host stays quarantined forever
                state.breaker.release_probe()
        if isinstance(exc, HOST_LEVEL_ERRORS):
            self._fail_or_reroute(rec, state, caller, exc)
        else:
            # the request's own outcome (deadline, bad prompt, model
            # error): pass through exactly once — the host already
            # accounted it
            self._resolve_caller(caller, exc=exc)

    def _fail_or_reroute(self, rec: _Placement, state: _HostState,
                         caller: Future, exc: BaseException) -> None:
        if not isinstance(exc, (HostDrainingError, QueueFullError)):
            # a drain or a full queue is planned backpressure, not a
            # host failure — only real failures feed the breaker
            self._record_host_failure(state, exc)
        elif rec.probe:
            self._record_host_failure(state, exc)
        expired = (rec.deadline is not None
                   and time.monotonic() >= rec.deadline)
        if rec.attempts < self.max_failovers and not expired:
            rec.attempts += 1
            rec.probe = False
            _M_FAILOVERS.inc()
            record_retry("host.submit", "retried")
            flight.record_event(
                "fabric.failover", host=state.host_id,
                attempt=rec.attempts, error=type(exc).__name__)
            try:
                self._dispatch(rec, caller, exclude=state)
                return
            except Exception as e:
                record_retry("host.submit", "exhausted")
                self._resolve_caller(caller, exc=e)
                return
        if self.max_failovers:
            record_retry("host.submit", "exhausted")
        self._resolve_caller(caller, exc=exc)

    def _record_host_failure(self, state: _HostState,
                             exc: BaseException) -> None:
        quarantined_now = False
        probe_failed = False
        with self._lock:
            now = time.monotonic()
            if state.probing and state.quarantined:
                # failed probation probe: stay out, back off harder
                state.breaker.record_probe_failure(now)
                probe_failed = True
            else:
                quarantined_now = state.breaker.record_failure(now)
        if probe_failed:
            flight.record_event(
                "fabric.probe_failed", host=state.host_id,
                next_probe_s=round(state.probation_backoff_s, 3),
                error=type(exc).__name__)
        if quarantined_now:
            _M_HOST_QUARANTINED.inc()
            # event + postmortem OUTSIDE the lock: the dump's providers
            # call snapshot(), which takes it again
            flight.record_event(
                "fabric.host_quarantined", host=state.host_id,
                failures=state.consecutive_failures,
                error=type(exc).__name__)
            flight.trigger_dump("host_failover", host=state.host_id)

    # -- refresh (digests, capacity, health) ---------------------------------
    def refresh(self) -> None:
        """Pull every host's capacity/digest/health once (handle calls
        outside the router lock). The auto-refresh thread calls this on
        its cadence; tests call it manually after seeding caches."""
        for state in list(self._hosts.values()):
            self._refresh_host(state)

    def _refresh_digest(self, state: _HostState) -> "HostDigest | None":
        """Advance one host's digest, delta-first (ISSUE 19): ask the
        host for the journal since the version we hold and fold it in —
        KBs/sec regardless of pool size — falling back to ONE wholesale
        snapshot on first contact, journal gaps, torn fetches
        (``digest.delta`` fault), or hosts that publish no journal
        (``prefix_digest_delta`` → None). Host-level errors propagate:
        the caller's unreachable-marking is about the HOST, not the
        refresh mode."""
        prev = state.digest
        if prev is not None:
            delta = None
            try:
                delta = state.handle.prefix_digest_delta(
                    prev.version, max_entries=self.digest_entries)
            except HOST_LEVEL_ERRORS:
                raise
            except Exception:
                # torn delta fetch: the journal said nothing usable —
                # re-sync wholesale below, same as a gap
                _M_DELTA_APPLIED.inc(outcome="error")
            else:
                if delta is not None:
                    advanced = prev.apply_delta(delta)
                    if advanced is not None:
                        _M_DELTA_BYTES.inc(len(json.dumps(delta)))
                        _M_DELTA_APPLIED.inc(
                            outcome=("applied" if advanced is not prev
                                     else "replayed"))
                        return advanced
                    _M_DELTA_APPLIED.inc(outcome="gap")
        snap = state.handle.prefix_digest(self.digest_entries)
        if snap is not None:
            _M_WHOLESALE_BYTES.inc(len(json.dumps(snap)))
        return HostDigest.from_snapshot(snap)

    def _refresh_host(self, state: _HostState) -> None:
        try:
            cap = state.handle.capacity()
            digest = self._refresh_digest(state)
            health = state.handle.health()
        except Exception as e:
            with self._lock:
                state.health_status = "unreachable"
            flight.record_event(
                "fabric.refresh_failed", host=state.host_id,
                error=type(e).__name__)
            return
        weight = (max(1, int(cap.get("replica_count") or 1))
                  * max(1, int(cap.get("n_slots") or 1)))
        saturation = self.max_outstanding
        if saturation is None:
            saturation = (int(cap.get("max_queue_depth") or 256)
                          + int(cap.get("n_slots") or 0))
        with self._lock:
            if self._hosts.get(state.host_id) is not state:
                # the host was removed (or replaced) while this poll
                # was in flight: publishing now would resurrect a
                # departed host's digest gauge/placement state
                return
            state.weight = weight
            state.saturation = saturation
            state.digest = digest
            fs = cap.get("free_slots")
            state.free_slots = int(fs) if fs is not None else None
            kf = cap.get("kv_blocks_free")
            state.kv_free = int(kf) if kf is not None else None
            kt = cap.get("kv_blocks_total")
            state.kv_total = int(kt) if kt is not None else None
            kc = cap.get("kv_blocks_cold")
            state.kv_cold = int(kc) if kc is not None else None
            ps = cap.get("kv_parked_sessions")
            state.kv_parked_sessions = (int(ps) if ps is not None
                                        else None)
            state.overload_level = int(cap.get("overload_level") or 0)
            state.health_status = str(
                health.get("status") or "ok")
            # gauge published under the same lock as the membership
            # check: remove_host's zeroing can never be overwritten by
            # a poll that raced the removal
            _M_DIGEST_BLOCKS.set(
                len(digest.hashes) if digest is not None else 0,
                host=state.host_id)

    def _refresh_worker(self) -> None:
        while not self._closing.wait(self.refresh_interval_s):
            try:
                self.refresh()
            except Exception:  # pragma: no cover - observability guard
                flight.record_event("fabric.refresh_error")

    # -- drain / lifecycle ---------------------------------------------------
    def drain_host(self, host_id: str, *,
                   wait_s: "float | None" = None,
                   migrate_parked: bool = True) -> int:
        """Gracefully drain one host for a rolling restart: no new
        placements, unstarted requests re-queued onto surviving hosts
        (queue-level :class:`Request` transfer for in-process hosts —
        trace ids/deadlines/Futures intact; transport hosts fail their
        blocked submits with :class:`HostDrainingError` and the
        failover path re-places them), in-flight requests finish where
        they are, and — unless ``migrate_parked=False`` — the host's
        PARKED sessions re-park on survivors chosen by the fleet-agreed
        rendezvous hash (ISSUE 19), so idle conversations resume with a
        page-in instead of a cold re-prefill. Returns the number of
        requests re-queued. ``wait_s`` blocks (bounded) until the
        router sees zero outstanding work on the host."""
        state = self._hosts.get(host_id)
        if state is None:
            raise KeyError(f"unknown fabric host {host_id!r}")
        with self._lock:
            state.draining = True
            self._purge_host_placement_state_locked(state)
        _M_DIGEST_BLOCKS.set(0, host=host_id)
        flight.record_event("fabric.drain_begin", host=host_id)
        try:
            reqs = state.handle.drain()
        except Exception as e:
            # one retry: a drain interrupted by a transient (or an
            # injected host.drain fault) must not strand the host
            # half-drained
            record_retry("host.drain", "retried")
            try:
                reqs = state.handle.drain()
            except Exception:
                record_retry("host.drain", "exhausted")
                raise
            record_retry("host.drain", "recovered")
            flight.record_event(
                "fabric.drain_retried", host=host_id,
                error=type(e).__name__)
        moved = self._requeue_requests(reqs)
        flight.record_event(
            "fabric.drain_requeued", host=host_id, requeued=moved)
        if migrate_parked:
            self._migrate_parked(state)
        if wait_s is not None:
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                with self._lock:
                    if state.outstanding <= 0:
                        break
                time.sleep(0.01)
        return moved

    def _migrate_parked(self, state: _HostState) -> int:
        """Move a draining host's parked sessions onto survivors, each
        to the host the fleet-agreed rendezvous hash of its path anchor
        picks — the SAME key a next-turn prompt extending that session
        hashes to, so stickiness re-derives without any router having
        to remember the move. Best-effort by design: any session a torn
        export/import drops simply re-prefills on resume (exactly the
        pre-migration cost), never fails a request. Returns sessions
        successfully adopted by survivors."""
        try:
            bundle = state.handle.export_parked_sessions()
        except Exception as e:
            flight.record_event(
                "fabric.migrate_export_failed", host=state.host_id,
                error=type(e).__name__)
            return 0
        if not bundle or not bundle.get("sessions"):
            return 0
        bs = int(bundle.get("block_size") or 0)
        with self._lock:
            survivors = sorted(
                hid for hid, s in self._hosts.items()
                if s is not state and not s.draining)
        if not survivors or bs < 1:
            return 0
        per_target: "dict[str, list]" = {}
        for sess in bundle["sessions"]:
            target = hrw_preferred_host(
                path_anchor(sess["tokens"], bs), survivors)
            per_target.setdefault(target, []).append(sess)
        moved = 0
        for hid, sessions in per_target.items():
            tstate = self._hosts.get(hid)
            if tstate is None:
                continue
            sub = dict(bundle)
            sub["sessions"] = sessions
            try:
                moved += int(tstate.handle.import_parked_sessions(sub))
            except Exception as e:
                flight.record_event(
                    "fabric.migrate_import_failed", host=hid,
                    error=type(e).__name__)
        flight.record_event(
            "fabric.migrate", host=state.host_id, sessions=moved,
            targets=sorted(per_target))
        return moved

    def requeue(self, reqs: "list[Request]") -> int:
        """Public transfer entry (ISSUE 16): hand already-accepted
        :class:`Request` objects to this fabric — the cross-TIER half
        of the drain contract. A :class:`~sparkdl_tpu.disagg.PhaseRouter`
        whose decode tier lost a KV handoff re-queues the victim here,
        at the chosen host's queue HEAD (``RequestQueue.requeue``), so
        it re-prefills ahead of later arrivals — zero accepted requests
        lost. Returns the number placed; unplaceable requests fail with
        the placement error, counted once."""
        return self._requeue_requests(reqs)

    def _requeue_requests(self, reqs: "list[Request]") -> int:
        """Hand drained :class:`Request` objects to surviving hosts:
        queue-level transfer where the target is in-process (the
        ``RequestQueue.requeue`` cross-queue contract), submit-and-
        bridge where it is remote. Requests that cannot be placed
        anywhere fail with the placement error — counted once, by this
        final owner."""
        if not reqs:
            return 0
        per_target: "dict[str, list[Request]]" = {}
        moved = 0
        for req in reqs:
            rec = _Placement(req.payload, None, None)
            rec.deadline = req.deadline
            try:
                state = self._place(rec, exclude=None, transfer=True)
            except Exception as e:
                self._fail_transferred(req, e)
                continue
            if hasattr(state.handle, "requeue"):
                per_target.setdefault(state.host_id, []).append(req)
                # the engine owns it now; the router's outstanding
                # charge from _place would never be repaid
                with self._lock:
                    state.outstanding -= 1
            else:
                try:
                    self._bridge_transfer(req, rec, state)
                except Exception as e:
                    # the surviving host refused at the door (raced
                    # drain/close): repay the charge, count the loss
                    # once, here — its final owner
                    with self._lock:
                        state.outstanding -= 1
                    self._fail_transferred(req, e)
                    continue
            moved += 1
        for host_id, batch in per_target.items():
            self._hosts[host_id].handle.requeue(batch)
            flight.record_event(
                "fabric.requeued", host=host_id, requests=len(batch),
                request_ids=[r.request_id for r in batch])
        if moved:
            _M_REQUEUED.inc(moved)
        return moved

    def _fail_transferred(self, req: Request, exc: BaseException) -> None:
        """A drained request that could not be re-placed anywhere dies
        here, counted exactly once (its original host recorded nothing —
        the no-double-count contract)."""
        from sparkdl_tpu.serving.queue import record_request_failure

        if req.started or req.future.set_running_or_notify_cancel():
            record_request_failure(exc, request_id=req.request_id)
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass

    def _bridge_transfer(self, req: Request, rec: _Placement,
                         state: _HostState) -> None:
        """Re-place one drained request on a remote host by submitting
        its payload and forwarding the result into the original
        Future (the transfer form queue-level requeue cannot reach)."""
        remaining = None
        if req.deadline is not None:
            remaining = max(0.001, req.deadline - time.monotonic())
        payload = req.payload
        if not isinstance(payload, dict):
            payload = {"prompt": payload.prompt,
                       "max_new_tokens": payload.max_new_tokens}
        inner = state.handle.submit(payload, timeout_s=remaining)
        if not req.started:
            req.future.set_running_or_notify_cancel()
            req.started = True

        def forward(f, req=req, state=state):
            with self._lock:
                state.outstanding -= 1
            exc = (CancelledError("host cancelled the request")
                   if f.cancelled() else f.exception())
            if exc is None:
                try:
                    req.future.set_result(f.result())
                except InvalidStateError:
                    pass
            else:
                self._fail_transferred(req, exc)

        inner.add_done_callback(forward)

    def _purge_host_placement_state_locked(self, state: _HostState
                                           ) -> None:
        """Forget everything that would steer NEW placements at a
        departing host (ISSUE 15): its sticky sessions re-place on
        survivors at their next turn instead of repeatedly failing over
        to a drained/removed host, and its cached prefix digest stops
        feeding affinity scores for a cache that is about to vanish."""
        state.digest = None
        for k in [k for k, v in self._sessions.items()
                  if v == state.host_id]:
            del self._sessions[k]

    # -- elasticity (ISSUE 15: the autoscaler's fabric actuators) ------------
    def add_host(self, handle: HostHandle) -> str:
        """Join one host to the fabric at runtime (fleet scale-up, or
        the revert of a not-yet-drained scale-down). The host starts
        taking placements as soon as the post-add refresh seeds its
        capacity/digest/health. Returns the host id."""
        state = _HostState(handle, self.max_outstanding,
                           self._make_breaker())
        with self._lock:
            if self._closed:
                raise RuntimeError("Router is closed")
            if state.host_id in self._hosts:
                raise ValueError(
                    f"duplicate host id {state.host_id!r}")
            self._hosts[state.host_id] = state
        flight.record_event(
            "fabric.host_added", host=state.host_id,
            hosts=len(self._hosts))
        # seed only the NEW host (the background thread keeps the rest
        # fresh): joining must not cost O(fleet) handle round-trips
        self._refresh_host(state)
        return state.host_id

    def remove_host(self, host_id: str, *, drain: bool = True
                    ) -> HostHandle:
        """Fleet scale-down: drain one host through the shared
        :meth:`drain_host` path (unstarted requests transfer to
        survivors — zero accepted requests lost) and forget it. The
        HANDLE is returned, not closed — the caller owns the host's
        lifecycle (the autoscaler parks it as spare capacity; an
        un-drained handle can rejoin via :meth:`add_host`). Raises
        ValueError when this is the last host."""
        with self._lock:
            if host_id not in self._hosts:
                raise KeyError(f"unknown fabric host {host_id!r}")
            if len(self._hosts) <= 1:
                raise ValueError(
                    "cannot remove the last fabric host; close() the "
                    "router to stop the fabric")
        if drain:
            requeued = self.drain_host(host_id)
        else:
            requeued = 0
        with self._lock:
            if host_id not in self._hosts:  # raced another removal
                raise KeyError(f"unknown fabric host {host_id!r}")
            if len(self._hosts) <= 1:
                # two concurrent removals of the last two hosts both
                # passed the pre-drain check: the loser stays (drained
                # but listed) rather than emptying the fleet
                raise ValueError(
                    "cannot remove the last fabric host; close() the "
                    "router to stop the fabric")
            state = self._hosts.pop(host_id)
            self._purge_host_placement_state_locked(state)
        _M_DIGEST_BLOCKS.set(0, host=host_id)
        flight.record_event(
            "fabric.host_removed", host=host_id, requeued=requeued,
            hosts=len(self._hosts))
        return state.handle

    def hosts(self) -> "list[str]":
        return list(self._hosts)

    @property
    def closed(self) -> bool:
        return self._closed

    def preferred_host(self, prompt) -> "str | None":
        """PURE fleet-agreed placement for ``prompt`` — the rendezvous
        max over ALL member host ids, ignoring load/health/digests.
        Every router over the same host set returns the same answer in
        any process (the cross-process determinism contract); live
        placement only diverges from it to follow load, affinity, or
        failures. None for an empty prompt."""
        if prompt is None or not len(prompt):
            return None
        with self._lock:
            host_ids = sorted(self._hosts)
            sizes = {s.digest.block_size
                     for s in self._hosts.values()
                     if s.digest is not None}
        pbs = self.placement_block_size or (min(sizes) if sizes else 16)
        return hrw_preferred_host(placement_key(prompt, pbs), host_ids)

    def host_handles(self) -> "list[HostHandle]":
        """Live handles (ISSUE 16): tier-level aggregations — e.g. the
        PhaseRouter's per-tier depth gauge and the per-tier autoscaler
        signal readers — poll ``capacity()`` across the fleet without
        reaching into router internals."""
        with self._lock:
            return [s.handle for s in self._hosts.values()]

    def fleet_hosts(self) -> "dict[str, HostHandle]":
        """``{host_id: handle}`` for the whole fleet (ISSUE 17) — what
        :meth:`~sparkdl_tpu.observability.fleet.FleetScraper.from_router`
        registers so the observability plane polls the same handles the
        router routes over."""
        with self._lock:
            return {hid: s.handle for hid, s in self._hosts.items()}

    def snapshot(self) -> "dict[str, Any]":
        """Operator/postmortem view. Exposes ``replica_count`` /
        ``healthy_count`` in the pool shape ``healthz_report()``
        aggregates — the fabric's hosts ARE this tier's replicas, so an
        all-hosts-down fabric degrades /healthz to unhealthy exactly
        like a dead replica pool would."""
        with self._lock:
            hosts = [
                {
                    "host": s.host_id,
                    "outstanding": s.outstanding,
                    "routed": s.routed,
                    "weight": s.weight,
                    "saturation": s.saturation,
                    "quarantined": s.quarantined,
                    "draining": s.draining,
                    "health": s.health_status,
                    "free_slots": s.free_slots,
                    "kv_free": s.kv_free,
                    "kv_total": s.kv_total,
                    "kv_cold": s.kv_cold,
                    "kv_parked_sessions": s.kv_parked_sessions,
                    "consecutive_failures": s.consecutive_failures,
                    "digest_blocks": (len(s.digest.hashes)
                                      if s.digest is not None else 0),
                    "digest_age_s": (round(s.digest.age_s(), 3)
                                     if s.digest is not None else None),
                }
                for s in self._hosts.values()
            ]
            sessions = len(self._sessions)
        healthy = sum(
            not h["quarantined"] and not h["draining"]
            and h["health"] not in ("unhealthy", "unreachable")
            for h in hosts)
        return {
            "policy": self.policy,
            "replica_count": len(hosts),
            "healthy_count": healthy,
            "hosts": hosts,
            "sessions": sessions,
        }

    def close(self) -> None:
        """Stop the router (refresh thread, registrations). Hosts are
        NOT closed — the caller owns their lifecycle (a router restart
        must not restart the fleet)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._closing.set()
        if self._refresh_thread is not None:
            self._refresh_thread.join(timeout=5)
        flight.record_event("fabric.close", router=self._flight_name)
        flight.remove_context_provider(self._flight_name)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
