"""Multi-host serving fabric: a cache-aware router tier over per-host
engines (ISSUE 14, ROADMAP item 1).

ReplicaPool scales across the chips of ONE host; this package is the
front door over MANY hosts — the coordinator/worker split of distributed
TensorFlow (arXiv 1603.04467, 1603.02339) applied to the serving tier.
Three layers, separately testable:

- :mod:`~sparkdl_tpu.fabric.host` — the uniform host surface
  (``submit/snapshot/capacity/health/prefix_digest/drain/close``):
  :class:`InProcessHost` wraps a live engine in this process (tests,
  the CPU harness, bench_serving's ``BENCH_HOSTS`` section) and defines
  :data:`HOST_LEVEL_ERRORS`, the retry class for failures that indict
  the host rather than the request.
- :mod:`~sparkdl_tpu.fabric.http` — the thin HTTP/json transport for
  real multi-process deployments (:class:`HostServer` over one engine,
  :class:`HttpHostHandle` on the router side), built on the same stdlib
  ``http.server`` machinery as the metrics exporter; remote errors
  re-raise as the same typed exceptions the in-process engine raises.
- :mod:`~sparkdl_tpu.fabric.router` — the :class:`Router`: weighted
  least-outstanding-work placement with prefix-cache **affinity**
  (hosts publish bounded prefix→host digests,
  :mod:`~sparkdl_tpu.fabric.digest`; requests sharing a cached prefix
  land where their blocks already live, capped so a hot prefix cannot
  hotspot one host), sticky sessions, spillover admission control,
  probation circuit-breaking with postmortem bundles on quarantine,
  host-level failover, and graceful :meth:`Router.drain_host` whose
  unstarted requests transfer queue-to-queue onto surviving hosts.
"""

from sparkdl_tpu.fabric.digest import (
    HostDigest,
    match_blocks,
    prompt_block_hashes,
)
from sparkdl_tpu.fabric.host import (
    HOST_LEVEL_ERRORS,
    HostDrainingError,
    HostHandle,
    HostUnavailableError,
    InProcessHost,
)
from sparkdl_tpu.fabric.http import HostServer, HttpHostHandle
from sparkdl_tpu.fabric.router import AllHostsUnavailableError, Router

__all__ = [
    "AllHostsUnavailableError",
    "HOST_LEVEL_ERRORS",
    "HostDigest",
    "HostDrainingError",
    "HostHandle",
    "HostServer",
    "HostUnavailableError",
    "HttpHostHandle",
    "InProcessHost",
    "Router",
    "match_blocks",
    "prompt_block_hashes",
]
