"""Multi-host serving fabric: a cache-aware router tier over per-host
engines (ISSUE 14, ROADMAP item 1).

ReplicaPool scales across the chips of ONE host; this package is the
front door over MANY hosts — the coordinator/worker split of distributed
TensorFlow (arXiv 1603.04467, 1603.02339) applied to the serving tier.
Three layers, separately testable:

- :mod:`~sparkdl_tpu.fabric.host` — the uniform host surface
  (``submit/snapshot/capacity/health/prefix_digest/drain/close``):
  :class:`InProcessHost` wraps a live engine in this process (tests,
  the CPU harness, bench_serving's ``BENCH_HOSTS`` section) and defines
  :data:`HOST_LEVEL_ERRORS`, the retry class for failures that indict
  the host rather than the request.
- :mod:`~sparkdl_tpu.fabric.http` — the thin HTTP/json transport for
  real multi-process deployments (:class:`HostServer` over one engine,
  :class:`HttpHostHandle` on the router side), built on the same stdlib
  ``http.server`` machinery as the metrics exporter; remote errors
  re-raise as the same typed exceptions the in-process engine raises.
- :mod:`~sparkdl_tpu.fabric.router` — the :class:`Router`: weighted
  least-outstanding-work placement with prefix-cache **affinity**
  (hosts publish bounded prefix→host digests,
  :mod:`~sparkdl_tpu.fabric.digest`; requests sharing a cached prefix
  land where their blocks already live, capped so a hot prefix cannot
  hotspot one host), sticky sessions, spillover admission control,
  probation circuit-breaking with postmortem bundles on quarantine,
  host-level failover, and graceful :meth:`Router.drain_host` whose
  unstarted requests transfer queue-to-queue onto surviving hosts (and
  whose parked sessions migrate to survivors, ISSUE 19).
- :mod:`~sparkdl_tpu.fabric.group` — the horizontally scaled router
  tier (ISSUE 19): N stateless routers agreeing on placement through
  rendezvous hashing (:func:`~sparkdl_tpu.fabric.digest.hrw_score`)
  instead of shared state, fronted by :class:`RouterGroup`
  (in-process) or :class:`RouterServer`/:class:`RouterHandle` (HTTP),
  with digest DELTAS keeping per-router refresh traffic ≤KBs/sec.
"""

from sparkdl_tpu.fabric.digest import (
    HostDigest,
    hrw_preferred_host,
    hrw_score,
    match_blocks,
    path_anchor,
    placement_key,
    prompt_block_hashes,
    session_key,
)
from sparkdl_tpu.fabric.group import (
    AllRoutersUnavailableError,
    RouterGroup,
    RouterHandle,
    RouterServer,
)
from sparkdl_tpu.fabric.host import (
    HOST_LEVEL_ERRORS,
    HostDrainingError,
    HostHandle,
    HostUnavailableError,
    InProcessHost,
)
from sparkdl_tpu.fabric.http import HostServer, HttpHostHandle
from sparkdl_tpu.fabric.router import AllHostsUnavailableError, Router

__all__ = [
    "AllHostsUnavailableError",
    "AllRoutersUnavailableError",
    "HOST_LEVEL_ERRORS",
    "HostDigest",
    "HostDrainingError",
    "HostHandle",
    "HostServer",
    "HostUnavailableError",
    "HttpHostHandle",
    "InProcessHost",
    "Router",
    "RouterGroup",
    "RouterHandle",
    "RouterServer",
    "hrw_preferred_host",
    "hrw_score",
    "match_blocks",
    "path_anchor",
    "placement_key",
    "prompt_block_hashes",
    "session_key",
]
