"""Online serving: async admission, dynamic micro-batching, continuous
GPT decode.

Every other inference surface in this framework is batch-mode — a caller
hands over a DataFrame and blocks until it drains. This package is the
online half the ROADMAP's "serves heavy traffic" north star requires:
requests arrive one at a time, asynchronously, and the engine coalesces
them into the bucketed, jit-cached device batches the batch stack already
compiles (tf.data's pipelining lesson applied to serving: decouple
request arrival from device dispatch and the chip never starves).

Three layers, separately testable:

- :mod:`~sparkdl_tpu.serving.queue` — bounded admission with deadlines
  and reject-with-error backpressure;
- :mod:`~sparkdl_tpu.serving.microbatcher` /
  :mod:`~sparkdl_tpu.serving.engine` — max-wait/max-batch dispatch into a
  :class:`~sparkdl_tpu.transformers._inference.BatchedRunner` (dp-sharded
  on multi-chip hosts), per-request error isolation, graceful drain;
- :mod:`~sparkdl_tpu.serving.continuous` — continuous batching for GPT
  generation: finished rows free their slot mid-stream, new prompts
  join the in-flight decode batch, greedy tokens stay identical to the
  unbatched decode. Default KV layout is block-paged
  (:mod:`~sparkdl_tpu.serving.kv_blocks` pool +
  :mod:`~sparkdl_tpu.serving.prefix_cache` radix prefix reuse +
  chunked prefill): memory bounded by live tokens, shared prompt
  prefixes served from cache, exhausted-pool admissions deferred in
  order; opt-in speculative multi-token decoding
  (:mod:`~sparkdl_tpu.serving.spec_decode` draft proposers, one
  verify dispatch per span, exact greedy acceptance) and bf16/int8
  quantized pool layouts;
- :mod:`~sparkdl_tpu.serving.replicas` — multi-device replica serving:
  one pinned jit-cached executor per local chip, micro-batches routed
  whole by least outstanding work, quarantine-on-repeated-failure, with
  readback pipelined through :mod:`~sparkdl_tpu.runtime.completion` so
  N chips serve N batches concurrently.

Observability (:mod:`~sparkdl_tpu.serving.metrics`): queue depth, batch
occupancy %, admission rejects, and p50/p95/p99 request latency via the
shared :func:`~sparkdl_tpu.observability.metrics.percentile` helpers.
"""

from sparkdl_tpu.serving.continuous import ContinuousGPTEngine, GenRequest
from sparkdl_tpu.serving.engine import ServingEngine
from sparkdl_tpu.serving.kv_blocks import KVBlockPool
from sparkdl_tpu.serving.metrics import ServingMetrics
from sparkdl_tpu.serving.microbatcher import MicroBatcher
from sparkdl_tpu.serving.prefix_cache import PrefixCache
from sparkdl_tpu.serving.queue import (
    DeadlineExceededError,
    EngineClosedError,
    QueueFullError,
    Request,
    RequestQueue,
    failure_reason,
)
from sparkdl_tpu.serving.replicas import (
    AllReplicasQuarantinedError,
    HungDispatchError,
    ReplicaPool,
)
from sparkdl_tpu.serving.spec_decode import (
    ChainedDraftSource,
    NGramDraftSource,
    PrefixCacheDraftSource,
)
from sparkdl_tpu.serving.tenancy import (
    PRIORITY_BACKGROUND,
    PRIORITY_INTERACTIVE,
    BrownoutShedError,
    OverloadController,
    TenantRegistry,
    TenantThrottledError,
)

__all__ = [
    "AllReplicasQuarantinedError",
    "BrownoutShedError",
    "ChainedDraftSource",
    "ContinuousGPTEngine",
    "DeadlineExceededError",
    "EngineClosedError",
    "GenRequest",
    "HungDispatchError",
    "KVBlockPool",
    "MicroBatcher",
    "NGramDraftSource",
    "OverloadController",
    "PRIORITY_BACKGROUND",
    "PRIORITY_INTERACTIVE",
    "PrefixCache",
    "PrefixCacheDraftSource",
    "QueueFullError",
    "ReplicaPool",
    "Request",
    "RequestQueue",
    "ServingEngine",
    "ServingMetrics",
    "TenantRegistry",
    "TenantThrottledError",
    "failure_reason",
]
