"""Draft proposers + greedy acceptance for speculative decoding.

Speculative decoding (ROADMAP item 3) splits one decode step into
*propose* (cheap, host-side or small-model) and *verify* (one target-
model dispatch scoring the whole k-token draft span at once): the
target model's per-token cost is dominated by reading weights + KV
cache, so verifying k positions costs barely more than one, and every
accepted draft token is a model pass the engine never dispatches.

Under GREEDY decoding acceptance is exact, not probabilistic: the
verify pass yields the argmax continuation at every draft position, a
draft token is accepted iff it EQUALS the argmax its prefix implies,
and the first mismatch position already carries the corrected token —
so the accepted stream is bitwise-identical to one-token-at-a-time
decode no matter what the proposer suggested
(:func:`greedy_accept`). A bad draft costs wasted verify positions,
never a wrong token.

This module is the PROPOSE half. A draft source is anything with
``propose(context, k) -> list[int]`` (``context`` = prompt + tokens
produced so far, ids only — proposers never touch device state):

* :class:`NGramDraftSource` — prompt-lookup decoding: find the latest
  earlier occurrence of the context's trailing n-gram and propose the
  tokens that followed it. Zero extra weights; strong on repetitive
  spans (code, structured output, greedy loops).
* :class:`PrefixCacheDraftSource` — reads the PR 10 radix trie
  (:meth:`~sparkdl_tpu.serving.prefix_cache.PrefixCache.suggest`):
  when the context is a prefix of a cached longer prompt, the cached
  continuation is the draft. Zero extra weights.
* :class:`ChainedDraftSource` — first non-empty proposal wins; the
  engine default chains trie -> n-gram.

A learned draft MODEL plugs in through the same hook: wrap its decode
loop in ``propose`` and hand it to
``ContinuousGPTEngine(draft_source=...)`` — the engine only ever sees
token ids, so draft-model choice is a proposer detail, not an engine
change.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def greedy_accept(drafts: Sequence[int],
                  outputs: Sequence[int]) -> int:
    """Accepted draft-token count under exact greedy verification.

    ``outputs[j]`` is the target model's argmax at draft position ``j``
    (given the real context plus drafts ``[:j]``); ``drafts[j]`` is
    accepted iff it equals ``outputs[j]`` and every earlier draft was
    accepted. Returns ``m``: ``outputs[:m+1]`` are the real greedy
    tokens this verify produced (the +1 is the bonus token — the first
    output is unconditionally real, and after ``m`` accepted drafts
    ``outputs[m]`` is the correction/continuation).
    """
    m = 0
    for d, o in zip(drafts, outputs):
        if int(d) != int(o):
            break
        m += 1
    return m


class NGramDraftSource:
    """Propose the continuation of the latest earlier occurrence of the
    context's trailing n-gram (prompt-lookup decoding).

    Tries n-gram sizes ``max_n`` down to ``min_n`` and takes the first
    (longest-context) hit, preferring the MOST RECENT earlier
    occurrence that still has ``k`` continuation tokens available —
    recency tracks the local pattern a greedy model is currently
    extending, and the availability constraint keeps repetitive runs
    (where the freshest occurrence sits at the very tail) proposing
    FULL drafts instead of one-token stubs: a constant or periodic
    span then drafts its own cycle, the high-acceptance case
    speculation exists for.
    """

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got {min_n}..{max_n}")
        self.max_n = max_n
        self.min_n = min_n

    def propose(self, context: np.ndarray, k: int) -> "list[int]":
        ctx = np.asarray(context)
        for n in range(min(self.max_n, len(ctx) - 1), self.min_n - 1, -1):
            tail = ctx[-n:]
            # windows[i] == ctx[i:i+n]; match anywhere strictly before
            # the trailing occurrence itself
            win = np.lib.stride_tricks.sliding_window_view(ctx, n)
            hits = np.flatnonzero((win[:-1] == tail).all(axis=1))
            if hits.size:
                full = hits[hits + n + k <= len(ctx)]
                start = int(full[-1] if full.size else hits[-1]) + n
                return [int(t) for t in ctx[start:start + k]]
        return []


class PrefixCacheDraftSource:
    """Drafts from the radix prefix cache: cached prompts that EXTEND
    the current context donate their continuation (ids only — see
    ``PrefixCache.suggest``)."""

    def __init__(self, prefix_cache):
        self._cache = prefix_cache

    def propose(self, context: np.ndarray, k: int) -> "list[int]":
        return self._cache.suggest(
            tuple(int(t) for t in context), k)


class ChainedDraftSource:
    """First source with a non-empty proposal wins."""

    def __init__(self, *sources):
        if not sources:
            raise ValueError("need at least one draft source")
        self.sources = sources

    def propose(self, context: np.ndarray, k: int) -> "list[int]":
        for s in self.sources:
            got = s.propose(context, k)
            if got:
                return got
        return []


def default_draft_source(prefix_cache=None,
                         max_n: int = 3) -> ChainedDraftSource:
    """The engine default: radix-trie continuations first (exact cached
    prompts beat statistics), n-gram self-lookup as fallback."""
    ngram = NGramDraftSource(max_n=max_n)
    if prefix_cache is None:
        return ChainedDraftSource(ngram)
    return ChainedDraftSource(
        PrefixCacheDraftSource(prefix_cache), ngram)
