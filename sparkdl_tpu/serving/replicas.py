"""Multi-device replica serving: one jit-cached executor per local chip.

``BatchedRunner``'s automatic data parallelism splits ONE batch across
the local devices — right for throughput-bound batch jobs, wrong for
online serving, where micro-batches are small (splitting a 32-row batch
8 ways leaves every chip at 4-row occupancy) and the serialization point
is the single dispatch loop. A :class:`ReplicaPool` is the replicated
alternative (the replicated-execution design of TensorFlow, Abadi et
al., applied to the serving stack): each local device gets its OWN
pinned :class:`~sparkdl_tpu.transformers._inference.BatchedRunner` —
own jit cache, own buckets, own ChainPolicy — and assembled
micro-batches are routed whole to the replica with the least
outstanding work. N chips serve N micro-batches concurrently; outputs
stay bitwise identical to the single-device engine because every
replica runs the exact same jitted program.

Contracts:

- **Routing**: least-outstanding-work (queued + running batches), ties
  broken round-robin. Per-replica depth/latency land in the metrics
  spine (``sparkdl_replica_depth{replica=...}``,
  ``sparkdl_replica_batch_seconds{replica=...}``).
- **Failure isolation with rider protection**: a batch whose executor
  fails is **re-routed once** to a different replica before its riders
  ever see an error (``sparkdl_retries_total{site="replica.execute"}``
  counts it); only a second failure surfaces. The micro-batcher's
  poison-row fallback then still retries rows individually.
- **Quarantine is a circuit breaker, not a death sentence**:
  ``max_failures`` *consecutive* executor failures quarantine the
  replica — it stops taking work, its queue re-routes — but after
  ``probation_s`` it receives ONE probation probe (a live batch, rider
  protected by the re-route). Probe success reintegrates the replica
  (``sparkdl_replica_reintegrated_total``); probe failure doubles the
  backoff up to ``probation_max_s``. Only an all-quarantined,
  none-probeable pool refuses work.
- **Hung-dispatch watchdog**: with ``dispatch_timeout_s`` set, a
  dispatch that exceeds the deadline is taken away from its replica —
  re-routed under the same rider protection as an executor error, so
  :class:`HungDispatchError` only surfaces once re-routes are exhausted
  — and the replica is quarantined as hung
  (``sparkdl_replica_hung_total``) instead of wedging the pool. The
  hung-freeze (no probation probes) lifts as soon as the wedged program
  resolves either way: a late success rejoins the replica directly, a
  late error re-enters the normal probation cycle.
- **Drain**: ``close(drain=True)`` serves every accepted batch before
  stopping; ``drain=False`` fails queued batches immediately.
- **Elasticity** (ISSUE 15): ``add_replica``/``remove_replica`` resize
  the pool at runtime — the autoscaler's replica actuator. Scale-down
  is drain-safe (unstarted work re-routes to survivors, the in-flight
  batch finishes on the victim) and carries the ``replica.scale_down``
  fault site so chaos plans can abort a scale event before it moves
  state. The quarantine/probation machinery is the shared
  :class:`~sparkdl_tpu.reliability.breaker.ProbationBreaker` (one
  implementation with the fabric router).

Drop-in: the pool exposes ``run_batch`` / ``run_batch_async`` /
``chunk_size``, so ``ServingEngine(ReplicaPool(...))`` works unchanged
— the micro-batcher keeps up to ``max_inflight_batches`` (= healthy
replicas + 1) dispatches in flight so every chip stays busy.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable, NamedTuple

import numpy as np

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability.metrics import StepMeter
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import attach, current_context, span
from sparkdl_tpu.reliability.breaker import ProbationBreaker
from sparkdl_tpu.reliability.faults import fault_point
from sparkdl_tpu.reliability.retry import record_retry
from sparkdl_tpu.transformers._inference import BatchedRunner

__all__ = [
    "AllReplicasQuarantinedError",
    "HungDispatchError",
    "ReplicaPool",
]

_log = logging.getLogger(__name__)

_METRICS = None


class _PoolMetrics(NamedTuple):
    """Lazy spine handles; the first three are labelled by replica."""

    depth: Any
    batch_seconds: Any
    batches: Any
    quarantined: Any
    reintegrated: Any
    hung: Any


def _metrics() -> _PoolMetrics:
    global _METRICS
    if _METRICS is None:
        _METRICS = _PoolMetrics(
            depth=registry().gauge(
                "sparkdl_replica_depth",
                "batches queued+running on each serving replica",
                labels=("replica",)),
            batch_seconds=registry().histogram(
                "sparkdl_replica_batch_seconds",
                "per-replica batch wall time, dispatch to host result",
                labels=("replica",)),
            batches=registry().counter(
                "sparkdl_replica_batches_total",
                "batches served by each replica", labels=("replica",)),
            quarantined=registry().counter(
                "sparkdl_replica_quarantined_total",
                "replicas quarantined after repeated executor failures"),
            reintegrated=registry().counter(
                "sparkdl_replica_reintegrated_total",
                "quarantined replicas that rejoined after a successful "
                "probation probe"),
            hung=registry().counter(
                "sparkdl_replica_hung_total",
                "dispatches failed by the hung-dispatch watchdog"),
        )
    return _METRICS


class AllReplicasQuarantinedError(RuntimeError):
    """Every replica in the pool is quarantined and none is due a
    probation probe; the pool cannot accept work right now."""


class HungDispatchError(TimeoutError):
    """A dispatch exceeded the pool's ``dispatch_timeout_s`` deadline
    and was failed by the watchdog (its replica is quarantined as
    hung)."""


class _Work:
    """One routed micro-batch: arrays in, Future-like out.

    Resolution is idempotent (``finish``/``fail`` first-writer-wins):
    the hung-dispatch watchdog may fail a batch whose wedged executor
    later completes it — the late result is discarded, never raced.
    """

    __slots__ = ("arrays", "result", "exc", "done", "retries", "probe",
                 "reroutable", "owner", "started_at", "trace_ctx", "_lock")

    def __init__(self, arrays: dict[str, np.ndarray]):
        self.arrays = arrays
        #: the dispatching batch's trace context (captured at submit so
        #: the replica worker's spans land in the riders' linked trace)
        self.trace_ctx = None
        self.result: Any = None
        self.exc: "BaseException | None" = None
        self.done = threading.Event()
        #: re-routes consumed (rider protection: at most max_reroutes)
        self.retries = 0
        #: replica currently responsible for resolving this work. The
        #: watchdog re-routes work whose executor is WEDGED (still
        #: running), so two replicas can hold the same work — only the
        #: owner's FAILURE may resolve it (a stale success is harmless:
        #: same program, same arrays, identical payload).
        self.owner: "object | None" = None
        #: warmup pins work to ONE replica: re-routing its batch would
        #: mask that replica's compile failure as a pool-wide success
        self.reroutable = True
        #: this routing is a probation probe of a quarantined replica
        self.probe = False
        #: monotonic start of the in-flight dispatch (watchdog input)
        self.started_at: "float | None" = None
        self._lock = threading.Lock()

    def finish(self, result: Any) -> None:
        with self._lock:
            if self.done.is_set():
                return  # watchdog got here first: late result discarded
            self.result = result
            self.done.set()

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self.done.is_set():
                return
            self.exc = exc
            self.done.set()

    # Future-like surface (what MicroBatcher/BatchResult callers use)
    def wait_result(self, timeout: "float | None" = None):
        if not self.done.wait(timeout):
            # same exception type BatchResult raises (they are distinct
            # classes on 3.10): pool and single-runner futures must be
            # interchangeable to caller retry logic
            raise FuturesTimeoutError("replica batch still in flight")
        if self.exc is not None:
            raise self.exc
        return self.result


class _PoolFuture:
    """Caller handle for one pool dispatch (matches
    :class:`~sparkdl_tpu.transformers._inference.BatchResult`'s
    ``result()`` surface)."""

    __slots__ = ("_work",)

    def __init__(self, work: _Work):
        self._work = work

    def result(self, timeout: "float | None" = None):
        return self._work.wait_result(timeout)


class _Replica:
    """One device's executor: pinned runner + worker thread + queue."""

    def __init__(self, index: int, device: Any, runner: BatchedRunner,
                 pool: "ReplicaPool"):
        self.index = index
        self.device = device
        self.runner = runner
        self.pool = pool
        self.queue: "queue_mod.Queue[_Work | None]" = queue_mod.Queue()
        #: queued + running batches (the routing signal), under pool lock
        self.outstanding = 0
        self.dispatched = 0
        #: the shared quarantine/probation state machine (mutated under
        #: the pool lock — reliability.breaker is the one implementation
        #: this pool and the fabric router both run)
        self.breaker = ProbationBreaker(
            max_failures=pool.max_failures,
            probation_s=pool.probation_s,
            probation_max_s=pool.probation_max_s,
        )
        #: quarantined because the watchdog caught a wedged dispatch:
        #: no probation probes until the wedged program resolves (probing
        #: would queue live work behind a stuck thread)
        self.hung = False
        #: the in-flight work item, if any (watchdog scan target)
        self.current_work: "_Work | None" = None
        self.latency = StepMeter(n_chips=1, window=256, warmup_steps=0)
        self.thread = threading.Thread(
            target=self._loop, name=f"sparkdl-replica-{index}", daemon=True
        )
        self.thread.start()

    # breaker state read-throughs (tests and snapshots read these; all
    # WRITES go through the breaker's transition verbs under pool lock)
    @property
    def quarantined(self) -> bool:
        return self.breaker.quarantined

    @property
    def probing(self) -> bool:
        return self.breaker.probing

    @property
    def consecutive_failures(self) -> int:
        return self.breaker.consecutive_failures

    @property
    def probation_until(self) -> float:
        return self.breaker.probation_until

    @property
    def probation_backoff_s(self) -> float:
        return self.breaker.probation_backoff_s

    def _loop(self) -> None:
        m = _metrics()
        depth, wall_hist, batches = m.depth, m.batch_seconds, m.batches
        label = str(self.index)
        while True:
            work = self.queue.get()
            if work is None:
                return
            work.started_at = time.monotonic()
            self.current_work = work
            t0 = time.perf_counter()
            exc: "Exception | None" = None
            result = None
            try:
                # re-root on the batch's trace so the replica span (and
                # the runner's device_step span under it) land in the
                # riders' linked trace
                with attach(work.trace_ctx), \
                        span("serving.replica_batch", replica=self.index):
                    fault_point("replica.execute")
                    result = self.runner.run_batch(work.arrays)
            except BaseException as e:
                exc = e if isinstance(e, Exception) else RuntimeError(
                    f"replica {self.index} executor died: {e!r}"
                )
            wall = time.perf_counter() - t0
            self.dispatched += 1
            self.current_work = None
            with self.pool._lock:
                self.outstanding -= 1
            # the work MUST resolve no matter what the accounting below
            # does — an unresolved _Work strands its caller forever, so
            # even the metrics calls live inside this guard
            try:
                depth.set(self.outstanding, replica=label)
                wall_hist.observe(wall, replica=label)
                batches.inc(replica=label)
                self.latency.record(wall, examples=1)
                if exc is None:
                    self.pool._on_success(self, work)
                    work.finish(result)
                else:
                    self.pool._on_failure(self, work, exc)
            except BaseException as account_exc:  # pragma: no cover
                work.fail(exc if exc is not None else account_exc)
                _log.exception(
                    "replica %d failure accounting raised", self.index
                )


class ReplicaPool:
    """Route micro-batches over one pinned executor per local device.

    ``apply_fn``/``batch_size``/``runner_kwargs`` build a
    :class:`BatchedRunner` per device (``data_parallel=False``,
    ``device=`` pinned); pass ``make_runner(device) -> BatchedRunner``
    instead for full control of each replica's construction (the
    failure-injection tests do). ``devices`` defaults to every local
    device; passing more replicas than devices round-robins devices
    ("simulated replicas" — how the CPU harness exercises N-way routing
    on one chip).

    Reliability knobs: ``max_failures`` consecutive failures open the
    circuit breaker; ``probation_s`` (None disables probes → permanent
    quarantine, the pre-reliability behavior) schedules the first
    probation probe, doubling per failed probe up to
    ``probation_max_s``; ``max_reroutes`` bounds rider-protecting
    re-routes per batch; ``dispatch_timeout_s`` (None disables) arms the
    hung-dispatch watchdog.
    """

    def __init__(self, apply_fn: "Callable | None" = None, *,
                 batch_size: int = 64,
                 devices: "list | None" = None,
                 n_replicas: "int | None" = None,
                 make_runner: "Callable[[Any], BatchedRunner] | None" = None,
                 partitioner_factory: "Callable[[Any], Any] | None" = None,
                 max_failures: int = 3,
                 probation_s: "float | None" = 1.0,
                 probation_max_s: float = 30.0,
                 max_reroutes: int = 1,
                 dispatch_timeout_s: "float | None" = None,
                 **runner_kwargs):
        import jax

        if (apply_fn is None) == (make_runner is None):
            raise ValueError(
                "pass exactly one of apply_fn or make_runner"
            )
        if max_failures < 1:
            raise ValueError(f"max_failures must be >= 1, got {max_failures}")
        if probation_s is not None and probation_s <= 0:
            raise ValueError(
                f"probation_s must be > 0 or None, got {probation_s}"
            )
        if max_reroutes < 0:
            raise ValueError(f"max_reroutes must be >= 0, got {max_reroutes}")
        if dispatch_timeout_s is not None and dispatch_timeout_s <= 0:
            raise ValueError(
                f"dispatch_timeout_s must be > 0 or None, got "
                f"{dispatch_timeout_s}"
            )
        if devices is None:
            devices = list(jax.local_devices())
        if n_replicas is None:
            n_replicas = len(devices)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if make_runner is None:
            # each executor's placement goes through a Partitioner
            # (sparkdl_tpu/partition): one SingleDevicePartitioner per
            # replica by default — the pool scales by REPLICATING
            # single-device partitioners, never by splitting batches.
            # partitioner_factory(device) swaps in a custom layout per
            # replica (e.g. an SPMDPartitioner over a per-replica
            # sub-mesh for models bigger than one chip).
            from sparkdl_tpu.partition import SingleDevicePartitioner

            if partitioner_factory is None:
                def partitioner_factory(device):
                    return SingleDevicePartitioner(device)

            def make_runner(device):
                return BatchedRunner(
                    apply_fn, batch_size=batch_size, data_parallel=False,
                    partitioner=partitioner_factory(device),
                    **runner_kwargs,
                )
        elif partitioner_factory is not None:
            raise ValueError(
                "partitioner_factory configures the DEFAULT runner "
                "construction; with make_runner= the caller owns the "
                "runner (give its BatchedRunner a partitioner directly)"
            )
        self.max_failures = max_failures
        self.probation_s = probation_s
        self.probation_max_s = probation_max_s
        self.max_reroutes = max_reroutes
        self.dispatch_timeout_s = dispatch_timeout_s
        self._lock = threading.Lock()
        self._closed = False
        self._closing = threading.Event()
        self._rr = 0  # round-robin tiebreak cursor
        #: elasticity (ISSUE 15): add_replica builds new executors from
        #: the same factory/device ring construction used
        self._make_runner = make_runner
        self._devices = list(devices)
        self._next_index = n_replicas
        #: replicas removed by scale-down whose worker has not exited
        #: yet: the watchdog keeps scanning them so an in-flight batch
        #: that wedges AFTER removal still gets deadline-failed
        self._retiring: "list[_Replica]" = []
        #: replicas mid-warmup in add_replica (not yet routable): on the
        #: watchdog scan so a wedged warmup dispatch is deadline-failed
        #: — surfacing from add_replica — instead of blocking it forever
        self._warming: "list[_Replica]" = []
        self.replicas = [
            _Replica(i, devices[i % len(devices)],
                     make_runner(devices[i % len(devices)]), self)
            for i in range(n_replicas)
        ]
        self._worker_ids = {r.thread.ident: r for r in self.replicas}
        # postmortem bundles + /healthz read live quarantine state from
        # this provider (removed at close)
        self._flight_name = flight.add_context_provider(
            f"pool-{id(self):x}", self.snapshot
        )
        flight.record_event(
            "pool.start", pool=self._flight_name,
            replicas=len(self.replicas),
        )
        self._watchdog: "threading.Thread | None" = None
        if dispatch_timeout_s is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="sparkdl-pool-watchdog",
                daemon=True,
            )
            self._watchdog.start()

    # -- the BatchedRunner-compatible surface --------------------------------
    @property
    def chunk_size(self) -> int:
        return self.replicas[0].runner.chunk_size

    @property
    def max_inflight_batches(self) -> int:
        """Dispatches the micro-batcher should keep in flight: one per
        healthy replica plus one assembling."""
        return max(1, sum(not r.quarantined for r in self.replicas)) + 1

    def run_batch_async(self, arrays: dict[str, np.ndarray]) -> _PoolFuture:
        """Route one assembled micro-batch; returns a future resolving
        to the same output ``BatchedRunner.run_batch`` produces."""
        work = _Work(arrays)
        work.trace_ctx = current_context()  # None with tracing off
        self._route(work)
        return _PoolFuture(work)

    def run_batch(self, arrays: dict[str, np.ndarray]):
        """Synchronous dispatch. Called FROM a replica worker thread (the
        micro-batcher's per-row poison fallback resolving inside a
        completion path) it executes inline on that replica instead of
        re-queueing — a self-routed wait would deadlock the worker."""
        me = self._worker_ids.get(threading.get_ident())
        if me is not None:
            return me.runner.run_batch(arrays)
        return self.run_batch_async(arrays).result()

    # -- routing -------------------------------------------------------------
    def _route(self, work: _Work, exclude: "_Replica | None" = None) -> None:
        depth = _metrics().depth
        try:
            with self._lock:
                if self._closed:
                    raise RuntimeError("ReplicaPool is closed")
                replica = self._pick_locked(work, exclude)
                replica.outstanding += 1
                work.owner = replica
                depth.set(replica.outstanding, replica=str(replica.index))
                # the enqueue happens UNDER the pool lock: remove_replica
                # takes this lock to retire a victim, so a routing that
                # picked the victim has finished its put before the
                # drain/shutdown-sentinel sequence starts — work can
                # never land behind the sentinel and strand its caller
                replica.queue.put(work)
        except AllReplicasQuarantinedError:
            # outside the pool lock: the dump's context providers call
            # snapshot(), which takes it again
            flight.record_event(
                "pool.all_quarantined", replicas=len(self.replicas))
            flight.trigger_dump("all_replicas_quarantined")
            raise

    def _pick_locked(self, work: _Work,
                     exclude: "_Replica | None") -> _Replica:
        now = time.monotonic()
        # probation probe: a quarantined (not hung) replica whose backoff
        # elapsed takes this batch as its probe — the rider is protected
        # by the re-route-once retry, so a failed probe costs latency,
        # never a result. First-time routings only: a batch already
        # burned by one replica must land somewhere trustworthy.
        # (max_reroutes=0 disables probes too: a probe's rider is only
        # protected by the re-route, and "a failed probe costs latency,
        # never a result" is the contract.) One documented exception: in
        # an ALL-quarantined pool the probe has no healthy re-route
        # target — but without the probe this rider was getting
        # AllReplicasQuarantinedError anyway (and the pool could never
        # self-heal), so the last-ditch probe can only improve its odds;
        # _retry_or_fail surfaces that same typed error if it fails.
        if (self.probation_s is not None and self.max_reroutes >= 1
                and work.retries == 0):
            for r in self.replicas:
                if (r is not exclude and not r.hung
                        and r.breaker.probe_due(now)):
                    r.breaker.begin_probe()
                    work.probe = True
                    return r
        healthy = [r for r in self.replicas
                   if not r.quarantined and r is not exclude]
        if not healthy:
            if exclude is not None:
                raise RuntimeError(
                    f"no alternative replica to re-route off replica "
                    f"{exclude.index}"
                )
            raise AllReplicasQuarantinedError(
                f"all {len(self.replicas)} replicas quarantined "
                f"(>{self.max_failures} consecutive failures each) and "
                "none is due a probation probe yet"
            )
        # least outstanding work; round-robin among ties so idle
        # replicas share the trickle load instead of replica 0
        # absorbing it all
        best = min(r.outstanding for r in healthy)
        ties = [r for r in healthy if r.outstanding == best]
        replica = ties[self._rr % len(ties)]
        self._rr += 1
        return replica

    # -- success/failure accounting (called from worker threads) -------------
    def _on_success(self, replica: _Replica, work: _Work) -> None:
        rejoined = False
        with self._lock:
            # resolution claim (mirrors _on_failure): a watchdogged
            # dispatch that finally succeeded AFTER its work was
            # re-routed still heals the replica below, but the per-work
            # outcome (the "recovered" retry metric) belongs to the
            # claimant alone — else one re-routed batch counts its
            # recovery twice
            claimed = (work.owner is replica
                       and not work.done.is_set())
            if claimed:
                work.owner = None
            # circuit closes on success: probe success, or a watchdog-
            # flagged dispatch that eventually completed
            rejoined = replica.breaker.record_success()
            if rejoined:
                replica.hung = False
        if rejoined:
            _metrics().reintegrated.inc()
            flight.record_event(
                "replica.reintegrated", replica=replica.index)
            _log.info(
                "replica %d (%s) reintegrated after successful probe; "
                "%d healthy replica(s)",
                replica.index, replica.device,
                sum(not r.quarantined for r in self.replicas),
            )
        if claimed and work.retries:
            record_retry("replica.execute", "recovered")

    def _on_failure(self, replica: _Replica, work: _Work,
                    exc: Exception) -> None:
        now = time.monotonic()
        quarantined_now = False
        with self._lock:
            # resolution claim: the watchdog may have already taken this
            # work away (owner cleared / re-routed elsewhere) — then this
            # failure only feeds the replica accounting below, and the
            # retries/fail decision belongs to the claimant alone
            claimed = (work.owner is replica
                       and not work.done.is_set())
            if claimed:
                work.owner = None
            if replica.hung:
                # the wedged dispatch finally resolved — with an error,
                # but the worker thread is free again: lift the
                # hung-freeze so probation probes can reach the replica
                # (only _on_success closes the circuit entirely)
                replica.hung = False
                replica.breaker.schedule_probe(now)
            was_probe = work.probe and replica.quarantined
            probe_failed = False
            if was_probe:
                # failed probe: stay quarantined, back off exponentially
                replica.breaker.record_probe_failure(now)
                probe_failed = True
                _log.warning(
                    "replica %d probation probe failed; next probe in "
                    "%.2fs", replica.index, replica.probation_backoff_s,
                )
            else:
                quarantined_now = replica.breaker.record_failure(now)
        if probe_failed:
            flight.record_event(
                "replica.probe_failed", replica=replica.index,
                next_probe_s=round(replica.probation_backoff_s, 3),
                error=type(exc).__name__,
            )
        if quarantined_now:
            _metrics().quarantined.inc()
            # the flight event + postmortem trigger sit OUTSIDE the pool
            # lock (the dump's providers re-take it via snapshot())
            flight.record_event(
                "replica.quarantined", replica=replica.index,
                failures=replica.consecutive_failures,
                error=type(exc).__name__,
            )
            flight.trigger_dump(
                "replica_quarantined", replica=replica.index)
            _log.error(
                "replica %d (%s) quarantined after %d consecutive "
                "failures; pool continues on %d healthy replica(s)%s",
                replica.index, replica.device,
                replica.consecutive_failures,
                sum(not r.quarantined for r in self.replicas),
                ("" if self.probation_s is None
                 else f"; probation probe in {self.probation_s:.2f}s"),
            )
            # re-route work it already accepted: those batches deserve a
            # healthy executor, not a seat behind a broken one
            self._requeue_queued(replica)
        if claimed:
            self._retry_or_fail(work, exc, exclude=replica)

    def _retry_or_fail(self, work: _Work, exc: Exception,
                       exclude: "_Replica | None") -> None:
        """Rider protection: re-route a failed batch up to
        ``max_reroutes`` times before its error reaches the caller.

        Single-claimant: callers must first take the resolution claim
        (clear ``work.owner`` under the pool lock while it still points
        at their replica) — that is what keeps the watchdog and a late
        worker failure from racing on ``retries``/``fail`` for the same
        work."""
        if work.done.is_set():
            return  # already resolved
        if not work.reroutable:
            work.fail(exc)  # replica-pinned (warmup): its error surfaces
            return
        was_probe = work.probe
        if work.retries < self.max_reroutes:
            work.retries += 1
            work.probe = False
            record_retry("replica.execute", "retried")
            try:
                self._route(work, exclude=exclude)
                return
            except Exception:
                pass  # no alternative replica: surface the real error
        if self.max_reroutes:
            record_retry("replica.execute", "exhausted")
        if was_probe:
            # a failed last-ditch probe (no healthy re-route target):
            # the rider gets the same typed error it would have seen had
            # the probe never been attempted, with the executor's real
            # failure chained for diagnosis
            pool_err = AllReplicasQuarantinedError(
                f"all {len(self.replicas)} replicas quarantined; the "
                "probation probe this batch rode also failed"
            )
            pool_err.__cause__ = exc
            flight.record_event(
                "pool.all_quarantined", replicas=len(self.replicas),
                probe_failed=True,
            )
            flight.trigger_dump("all_replicas_quarantined")
            work.fail(pool_err)
            return
        work.fail(exc)

    def _requeue_queued(self, replica: _Replica) -> None:
        """Drain a quarantined/hung replica's queue back through
        routing (its own shutdown token is preserved)."""
        requeued = 0
        while True:
            try:
                work = replica.queue.get_nowait()
            except queue_mod.Empty:
                break
            if work is None:
                replica.queue.put(None)  # keep the shutdown token
                break
            with self._lock:
                replica.outstanding -= 1
            try:
                self._route(work)
                requeued += 1
            except Exception as e:
                work.fail(e)
        if requeued:
            _log.warning(
                "re-routed %d queued batch(es) off replica %d",
                requeued, replica.index,
            )

    # -- hung-dispatch watchdog ----------------------------------------------
    def _watchdog_loop(self) -> None:
        assert self.dispatch_timeout_s is not None
        interval = max(0.005, min(0.25, self.dispatch_timeout_s / 4.0))
        while not self._closing.wait(interval):
            now = time.monotonic()
            with self._lock:
                # retiring replicas stay scanned until their worker
                # exits (drop the ones that finished cleanly); warming
                # replicas are scanned so a wedged warmup dispatch
                # deadline-fails instead of blocking add_replica
                self._retiring = [r for r in self._retiring
                                  if r.thread.is_alive()]
                scan = (list(self.replicas) + list(self._retiring)
                        + list(self._warming))
            for r in scan:
                work = r.current_work
                if work is None or work.done.is_set():
                    continue
                t0 = work.started_at
                if t0 is None or now - t0 <= self.dispatch_timeout_s:
                    continue
                already = False
                with self._lock:
                    # re-verify under the lock: the worker clears
                    # current_work BEFORE its success/failure accounting,
                    # so a dispatch that completed since the unlocked
                    # read above is visible here — marking it hung would
                    # quarantine a healthy replica with no completion
                    # left to ever clear the flag
                    if r.current_work is not work or work.done.is_set():
                        continue
                    # resolution claim (same protocol as _on_failure):
                    # the wedged worker's current_work stays pointed at
                    # this work until its thread unwedges, so without
                    # the claim every later tick would re-fire on the
                    # stale reference and fail a batch that a previous
                    # tick already re-routed to a healthy replica
                    if work.owner is not r:
                        continue
                    work.owner = None
                    already = not r.breaker.trip()
                    r.hung = True
                _metrics().hung.inc()
                if not already:
                    _metrics().quarantined.inc()
                flight.record_event(
                    "replica.hung", replica=r.index,
                    timeout_s=self.dispatch_timeout_s,
                )
                flight.trigger_dump("hung_dispatch", replica=r.index)
                _log.error(
                    "watchdog: dispatch on replica %d exceeded %.2fs; "
                    "re-routing the batch and quarantining the replica "
                    "as hung (it rejoins if the wedged program "
                    "completes)", r.index, self.dispatch_timeout_s,
                )
                # rider protection applies to watchdogged work too: the
                # same re-route-once that covers executor errors (the
                # wedged executor's late completion is first-writer-wins
                # discarded by _Work's idempotent resolution)
                self._retry_or_fail(work, HungDispatchError(
                    f"dispatch on replica {r.index} exceeded the "
                    f"{self.dispatch_timeout_s}s deadline"
                ), exclude=r)
                self._requeue_queued(r)

    # -- lifecycle / introspection -------------------------------------------
    def close(self, *, drain: bool = True,
              timeout_s: "float | None" = 30.0) -> None:
        """Stop the pool. ``drain=True`` serves everything already
        routed first; ``drain=False`` fails queued batches now."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        flight.record_event(
            "pool.close", pool=self._flight_name, drain=drain)
        flight.remove_context_provider(self._flight_name)
        self._closing.set()
        for r in self.replicas:
            if not drain:
                while True:
                    try:
                        work = r.queue.get_nowait()
                    except queue_mod.Empty:
                        break
                    if work is not None:
                        work.fail(RuntimeError("ReplicaPool closed"))
            r.queue.put(None)  # wake + stop the worker after the drain
        for r in self.replicas:
            r.thread.join(timeout_s)
            if r.thread.is_alive():  # pragma: no cover - watchdog only
                _log.warning("replica %d did not stop in %ss",
                             r.index, timeout_s)
        if self._watchdog is not None:
            self._watchdog.join(timeout_s)

    # -- elasticity (ISSUE 15: the autoscaler's replica actuator) ------------
    def add_replica(self, *,
                    warmup_arrays: "dict[str, np.ndarray] | None" = None
                    ) -> int:
        """Grow the pool by one replica at runtime. The executor is
        built (and, with ``warmup_arrays``, compiled) BEFORE the replica
        joins routing, so live traffic never waits on a cold replica's
        first compile. Devices round-robin off the construction ring
        (the simulated-replica behavior on the CPU harness). Returns the
        new replica's index — indices are never reused, so flight events
        and per-replica metric labels stay unambiguous across scale
        cycles."""
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaPool is closed")
            index = self._next_index
            self._next_index += 1
        device = self._devices[index % len(self._devices)]
        replica = _Replica(index, device, self._make_runner(device), self)
        if warmup_arrays is not None:
            work = _Work(warmup_arrays)
            work.reroutable = False  # a failed warmup must SURFACE
            work.owner = replica
            with self._lock:
                replica.outstanding += 1
                self._warming.append(replica)
                replica.queue.put(work)
            try:
                # unbounded wait is safe: the watchdog scans _warming,
                # so with dispatch_timeout_s armed a wedged warmup is
                # deadline-failed (reroutable=False -> the error
                # surfaces here) exactly like a live replica's warmup
                _PoolFuture(work).result()
            except BaseException:
                replica.queue.put(None)  # never joined routing: stop it
                raise
            finally:
                with self._lock:
                    if replica in self._warming:
                        self._warming.remove(replica)
        with self._lock:
            if self._closed:
                replica.queue.put(None)
                raise RuntimeError("ReplicaPool is closed")
            self.replicas.append(replica)
            self._worker_ids[replica.thread.ident] = replica
        flight.record_event(
            "pool.scale_up", pool=self._flight_name, replica=index,
            replicas=len(self.replicas),
        )
        _log.info("replica %d (%s) added; pool now %d replica(s)",
                  index, device, len(self.replicas))
        return index

    def remove_replica(self, index: "int | None" = None, *,
                       timeout_s: "float | None" = 30.0) -> int:
        """Drain-safe scale-down: retire one replica with ZERO accepted
        batches lost. The victim (``index``, or auto-picked: a
        quarantined replica first, else the least-loaded) leaves routing
        immediately, its queued-but-unstarted work re-routes to
        survivors through the same requeue path a quarantine uses, and
        its in-flight batch finishes on the victim before the worker
        stops — the fleet-level drain contract (ISSUE 14) applied to
        one host's chips. ``replica.scale_down`` is a fault site AT THE
        TOP: an injected fault aborts the scale-down before any state
        moves, so the autoscaler defers the decision instead of losing
        work mid-drain. Raises ValueError below one replica."""
        fault_point("replica.scale_down")
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaPool is closed")
            if len(self.replicas) <= 1:
                raise ValueError(
                    "cannot scale below one replica; close() the pool "
                    "to stop serving")
            if index is not None:
                victim = next(
                    (r for r in self.replicas if r.index == index), None)
                if victim is None:
                    raise KeyError(f"no replica with index {index}")
            else:
                quarantined = [r for r in self.replicas if r.quarantined]
                victim = min(quarantined or self.replicas,
                             key=lambda r: r.outstanding)
            self.replicas.remove(victim)
            self._worker_ids.pop(victim.thread.ident, None)
            # the watchdog keeps scanning the victim until its worker
            # exits: an in-flight dispatch that wedges mid-retirement
            # is still deadline-failed instead of hanging its riders
            self._retiring.append(victim)
        # unstarted work transfers to survivors (the victim no longer
        # routes, so _route picks only live replicas); the in-flight
        # batch — if any — resolves on the victim's worker below
        self._requeue_queued(victim)
        victim.queue.put(None)  # stop the worker after its last batch
        victim.thread.join(timeout_s)
        if victim.thread.is_alive():  # pragma: no cover - wedged program
            _log.warning(
                "replica %d worker did not stop in %ss (wedged "
                "dispatch); its thread is daemon, off routing, and "
                "stays under watchdog scan until it exits",
                victim.index, timeout_s)
        else:
            with self._lock:
                if victim in self._retiring:
                    self._retiring.remove(victim)
        _metrics().depth.set(0, replica=str(victim.index))
        flight.record_event(
            "pool.scale_down", pool=self._flight_name,
            replica=victim.index, replicas=len(self.replicas),
        )
        _log.info("replica %d (%s) drained and removed; pool now %d "
                  "replica(s)", victim.index, victim.device,
                  len(self.replicas))
        return victim.index

    def warmup(self, arrays: dict[str, np.ndarray]) -> None:
        """Dispatch ``arrays`` to EVERY replica (compile its buckets)
        before measurement/traffic — steady-state serving never pays a
        first-request compile."""
        # route one copy to each replica directly (bypass least-work:
        # warmup must touch all of them)
        futs = []
        for r in self.replicas:
            work = _Work(arrays)
            work.reroutable = False  # a failed warmup must SURFACE
            work.owner = r
            with self._lock:
                if self._closed:
                    # a closed replica's worker has consumed its shutdown
                    # token: queued work would hang forever
                    raise RuntimeError("ReplicaPool is closed")
                r.outstanding += 1
                r.queue.put(work)
            futs.append(_PoolFuture(work))
        for f in futs:
            f.result()

    def snapshot(self) -> dict[str, Any]:
        """Operator view: per-replica depth, in-flight, totals,
        quarantine/probation state, latency percentiles."""
        now = time.monotonic()
        with self._lock:
            replicas = [
                {
                    "replica": r.index,
                    "device": str(r.device),
                    "depth": r.queue.qsize(),
                    "in_flight": r.outstanding,
                    "dispatched": r.dispatched,
                    "consecutive_failures": r.consecutive_failures,
                    "quarantined": r.quarantined,
                    "hung": r.hung,
                    "probing": r.probing,
                    "next_probe_in_s": (
                        max(0.0, r.probation_until - now)
                        if r.quarantined and not r.hung
                        and self.probation_s is not None else None
                    ),
                    "latency_s": r.latency.step_time_percentiles((50, 95)),
                }
                for r in self.replicas
            ]
        return {
            "replica_count": len(self.replicas),
            "healthy_count": sum(
                not r["quarantined"] for r in replicas),
            "replicas": replicas,
        }

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))
