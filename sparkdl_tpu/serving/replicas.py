"""Multi-device replica serving: one jit-cached executor per local chip.

``BatchedRunner``'s automatic data parallelism splits ONE batch across
the local devices — right for throughput-bound batch jobs, wrong for
online serving, where micro-batches are small (splitting a 32-row batch
8 ways leaves every chip at 4-row occupancy) and the serialization point
is the single dispatch loop. A :class:`ReplicaPool` is the replicated
alternative (the replicated-execution design of TensorFlow, Abadi et
al., applied to the serving stack): each local device gets its OWN
pinned :class:`~sparkdl_tpu.transformers._inference.BatchedRunner` —
own jit cache, own buckets, own ChainPolicy — and assembled
micro-batches are routed whole to the replica with the least
outstanding work. N chips serve N micro-batches concurrently; outputs
stay bitwise identical to the single-device engine because every
replica runs the exact same jitted program.

Contracts:

- **Routing**: least-outstanding-work (queued + running batches), ties
  broken round-robin. Per-replica depth/latency land in the metrics
  spine (``sparkdl_replica_depth{replica=...}``,
  ``sparkdl_replica_batch_seconds{replica=...}``).
- **Failure isolation**: a failed batch surfaces ITS error on ITS
  future (the micro-batcher's poison-row fallback then retries rows
  individually — routed to healthy replicas). ``max_failures``
  *consecutive* executor failures quarantine the replica: it stops
  taking work, its queue re-routes, and the pool keeps serving on the
  survivors. Only an all-replicas-quarantined pool refuses work.
- **Drain**: ``close(drain=True)`` serves every accepted batch before
  stopping; ``drain=False`` fails queued batches immediately.

Drop-in: the pool exposes ``run_batch`` / ``run_batch_async`` /
``chunk_size``, so ``ServingEngine(ReplicaPool(...))`` works unchanged
— the micro-batcher keeps up to ``max_inflight_batches`` (= healthy
replicas + 1) dispatches in flight so every chip stays busy.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable

import numpy as np

from sparkdl_tpu.observability.metrics import StepMeter
from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.transformers._inference import BatchedRunner

__all__ = ["AllReplicasQuarantinedError", "ReplicaPool"]

_log = logging.getLogger(__name__)

_METRICS = None


def _metrics():
    """Lazy spine handles: (depth gauge, batch-wall histogram, batches
    counter, quarantine counter), all labelled by replica index."""
    global _METRICS
    if _METRICS is None:
        _METRICS = (
            registry().gauge(
                "sparkdl_replica_depth",
                "batches queued+running on each serving replica",
                labels=("replica",)),
            registry().histogram(
                "sparkdl_replica_batch_seconds",
                "per-replica batch wall time, dispatch to host result",
                labels=("replica",)),
            registry().counter(
                "sparkdl_replica_batches_total",
                "batches served by each replica", labels=("replica",)),
            registry().counter(
                "sparkdl_replica_quarantined_total",
                "replicas quarantined after repeated executor failures"),
        )
    return _METRICS


class AllReplicasQuarantinedError(RuntimeError):
    """Every replica in the pool has been quarantined; the pool cannot
    accept work until it is rebuilt."""


class _Work:
    """One routed micro-batch: arrays in, Future-like out."""

    __slots__ = ("arrays", "result", "exc", "done")

    def __init__(self, arrays: dict[str, np.ndarray]):
        self.arrays = arrays
        self.result: Any = None
        self.exc: "BaseException | None" = None
        self.done = threading.Event()

    # Future-like surface (what MicroBatcher/BatchResult callers use)
    def wait_result(self, timeout: "float | None" = None):
        if not self.done.wait(timeout):
            # same exception type BatchResult raises (they are distinct
            # classes on 3.10): pool and single-runner futures must be
            # interchangeable to caller retry logic
            raise FuturesTimeoutError("replica batch still in flight")
        if self.exc is not None:
            raise self.exc
        return self.result


class _PoolFuture:
    """Caller handle for one pool dispatch (matches
    :class:`~sparkdl_tpu.transformers._inference.BatchResult`'s
    ``result()`` surface)."""

    __slots__ = ("_work",)

    def __init__(self, work: _Work):
        self._work = work

    def result(self, timeout: "float | None" = None):
        return self._work.wait_result(timeout)


class _Replica:
    """One device's executor: pinned runner + worker thread + queue."""

    def __init__(self, index: int, device: Any, runner: BatchedRunner,
                 pool: "ReplicaPool"):
        self.index = index
        self.device = device
        self.runner = runner
        self.pool = pool
        self.queue: "queue_mod.Queue[_Work | None]" = queue_mod.Queue()
        #: queued + running batches (the routing signal), under pool lock
        self.outstanding = 0
        self.dispatched = 0
        self.consecutive_failures = 0
        self.quarantined = False
        self.latency = StepMeter(n_chips=1, window=256, warmup_steps=0)
        self.thread = threading.Thread(
            target=self._loop, name=f"sparkdl-replica-{index}", daemon=True
        )
        self.thread.start()

    def _loop(self) -> None:
        depth, wall_hist, batches, _ = _metrics()
        label = str(self.index)
        while True:
            work = self.queue.get()
            if work is None:
                return
            t0 = time.perf_counter()
            try:
                with span("serving.replica_batch", replica=self.index):
                    work.result = self.runner.run_batch(work.arrays)
            except BaseException as e:
                work.exc = e if isinstance(e, Exception) else RuntimeError(
                    f"replica {self.index} executor died: {e!r}"
                )
                self.pool._on_failure(self)
            else:
                self.pool._on_success(self)
            finally:
                wall = time.perf_counter() - t0
                wall_hist.observe(wall, replica=label)
                batches.inc(replica=label)
                self.latency.record(wall, examples=1)
                self.dispatched += 1
                with self.pool._lock:
                    self.outstanding -= 1
                    depth.set(self.outstanding, replica=label)
                work.done.set()


class ReplicaPool:
    """Route micro-batches over one pinned executor per local device.

    ``apply_fn``/``batch_size``/``runner_kwargs`` build a
    :class:`BatchedRunner` per device (``data_parallel=False``,
    ``device=`` pinned); pass ``make_runner(device) -> BatchedRunner``
    instead for full control of each replica's construction (the
    failure-injection tests do). ``devices`` defaults to every local
    device; passing more replicas than devices round-robins devices
    ("simulated replicas" — how the CPU harness exercises N-way routing
    on one chip).
    """

    def __init__(self, apply_fn: "Callable | None" = None, *,
                 batch_size: int = 64,
                 devices: "list | None" = None,
                 n_replicas: "int | None" = None,
                 make_runner: "Callable[[Any], BatchedRunner] | None" = None,
                 max_failures: int = 3,
                 **runner_kwargs):
        import jax

        if (apply_fn is None) == (make_runner is None):
            raise ValueError(
                "pass exactly one of apply_fn or make_runner"
            )
        if max_failures < 1:
            raise ValueError(f"max_failures must be >= 1, got {max_failures}")
        if devices is None:
            devices = list(jax.local_devices())
        if n_replicas is None:
            n_replicas = len(devices)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if make_runner is None:
            def make_runner(device):
                return BatchedRunner(
                    apply_fn, batch_size=batch_size, data_parallel=False,
                    device=device, **runner_kwargs,
                )
        self.max_failures = max_failures
        self._lock = threading.Lock()
        self._closed = False
        self._rr = 0  # round-robin tiebreak cursor
        self.replicas = [
            _Replica(i, devices[i % len(devices)],
                     make_runner(devices[i % len(devices)]), self)
            for i in range(n_replicas)
        ]
        self._worker_ids = {r.thread.ident: r for r in self.replicas}

    # -- the BatchedRunner-compatible surface --------------------------------
    @property
    def chunk_size(self) -> int:
        return self.replicas[0].runner.chunk_size

    @property
    def max_inflight_batches(self) -> int:
        """Dispatches the micro-batcher should keep in flight: one per
        healthy replica plus one assembling."""
        return max(1, sum(not r.quarantined for r in self.replicas)) + 1

    def run_batch_async(self, arrays: dict[str, np.ndarray]) -> _PoolFuture:
        """Route one assembled micro-batch; returns a future resolving
        to the same output ``BatchedRunner.run_batch`` produces."""
        work = _Work(arrays)
        self._route(work)
        return _PoolFuture(work)

    def run_batch(self, arrays: dict[str, np.ndarray]):
        """Synchronous dispatch. Called FROM a replica worker thread (the
        micro-batcher's per-row poison fallback resolving inside a
        completion path) it executes inline on that replica instead of
        re-queueing — a self-routed wait would deadlock the worker."""
        me = self._worker_ids.get(threading.get_ident())
        if me is not None:
            return me.runner.run_batch(arrays)
        return self.run_batch_async(arrays).result()

    # -- routing -------------------------------------------------------------
    def _route(self, work: _Work) -> None:
        depth, _, _, _ = _metrics()
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaPool is closed")
            healthy = [r for r in self.replicas if not r.quarantined]
            if not healthy:
                raise AllReplicasQuarantinedError(
                    f"all {len(self.replicas)} replicas quarantined "
                    f"(>{self.max_failures} consecutive failures each); "
                    "rebuild the pool"
                )
            # least outstanding work; round-robin among ties so idle
            # replicas share the trickle load instead of replica 0
            # absorbing it all
            best = min(r.outstanding for r in healthy)
            ties = [r for r in healthy if r.outstanding == best]
            replica = ties[self._rr % len(ties)]
            self._rr += 1
            replica.outstanding += 1
            depth.set(replica.outstanding, replica=str(replica.index))
        replica.queue.put(work)

    # -- failure accounting (called from worker threads) ---------------------
    def _on_success(self, replica: _Replica) -> None:
        replica.consecutive_failures = 0

    def _on_failure(self, replica: _Replica) -> None:
        replica.consecutive_failures += 1
        if (replica.consecutive_failures >= self.max_failures
                and not replica.quarantined):
            with self._lock:
                replica.quarantined = True
            _metrics()[3].inc()
            _log.error(
                "replica %d (%s) quarantined after %d consecutive "
                "failures; pool continues on %d healthy replica(s)",
                replica.index, replica.device,
                replica.consecutive_failures,
                sum(not r.quarantined for r in self.replicas),
            )
            # re-route work it already accepted: those batches deserve a
            # healthy executor, not a seat behind a broken one
            requeued = 0
            while True:
                try:
                    work = replica.queue.get_nowait()
                except queue_mod.Empty:
                    break
                if work is None:
                    replica.queue.put(None)  # keep the shutdown token
                    break
                with self._lock:
                    replica.outstanding -= 1
                try:
                    self._route(work)
                    requeued += 1
                except Exception as e:
                    work.exc = e
                    work.done.set()
            if requeued:
                _log.warning(
                    "re-routed %d queued batch(es) off quarantined "
                    "replica %d", requeued, replica.index,
                )

    # -- lifecycle / introspection -------------------------------------------
    def close(self, *, drain: bool = True,
              timeout_s: "float | None" = 30.0) -> None:
        """Stop the pool. ``drain=True`` serves everything already
        routed first; ``drain=False`` fails queued batches now."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for r in self.replicas:
            if not drain:
                while True:
                    try:
                        work = r.queue.get_nowait()
                    except queue_mod.Empty:
                        break
                    if work is not None:
                        work.exc = RuntimeError("ReplicaPool closed")
                        work.done.set()
            r.queue.put(None)  # wake + stop the worker after the drain
        for r in self.replicas:
            r.thread.join(timeout_s)
            if r.thread.is_alive():  # pragma: no cover - watchdog only
                _log.warning("replica %d did not stop in %ss",
                             r.index, timeout_s)

    def warmup(self, arrays: dict[str, np.ndarray]) -> None:
        """Dispatch ``arrays`` to EVERY replica (compile its buckets)
        before measurement/traffic — steady-state serving never pays a
        first-request compile."""
        # route one copy to each replica directly (bypass least-work:
        # warmup must touch all of them)
        futs = []
        for r in self.replicas:
            work = _Work(arrays)
            with self._lock:
                if self._closed:
                    # a closed replica's worker has consumed its shutdown
                    # token: queued work would hang forever
                    raise RuntimeError("ReplicaPool is closed")
                r.outstanding += 1
                r.queue.put(work)
            futs.append(_PoolFuture(work))
        for f in futs:
            f.result()

    def snapshot(self) -> dict[str, Any]:
        """Operator view: per-replica depth, in-flight, totals,
        quarantine state, latency percentiles."""
        with self._lock:
            replicas = [
                {
                    "replica": r.index,
                    "device": str(r.device),
                    "depth": r.queue.qsize(),
                    "in_flight": r.outstanding,
                    "dispatched": r.dispatched,
                    "consecutive_failures": r.consecutive_failures,
                    "quarantined": r.quarantined,
                    "latency_s": r.latency.step_time_percentiles((50, 95)),
                }
                for r in self.replicas
            ]
        return {
            "replica_count": len(self.replicas),
            "healthy_count": sum(
                not r["quarantined"] for r in replicas),
            "replicas": replicas,
        }

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))
