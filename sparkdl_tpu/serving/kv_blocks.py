"""Block-paged KV allocation for continuous GPT serving.

The dense continuous engine holds one ``[layers, n_slots, max_len, H, D]``
cache, so its memory contract is ``n_slots x max_len`` worst-case columns
whether or not tokens exist. This module is the host-side half of the
paged layout (ROADMAP item 4, the vLLM idea): the device holds one
``[layers, n_blocks, block_size, H, D]`` pool
(:func:`~sparkdl_tpu.models.gpt.init_block_pool`), each serving slot maps
its logical columns onto pool blocks through a per-slot block table, and
THIS class owns the free list and refcounts — so

* capacity is bounded by live tokens (``blocks_used x block_size``), not
  by ``n_slots x max_len``;
* a physical block can back many slots at once (refcounted — how
  :mod:`~sparkdl_tpu.serving.prefix_cache` shares prompt prefixes);
* admission against an exhausted pool *defers* (the engine re-queues the
  request and retries as slots retire) instead of erroring.

Bookkeeping is plain Python under the engine lock — allocation is a
host-side scheduling decision, never device work. The pool publishes
``sparkdl_kv_blocks_total`` / ``sparkdl_kv_blocks_used`` /
``sparkdl_kv_blocks_spare`` gauges as delta contributions (several
pools may live in one process; each adds its share instead of
clobbering the others — the RequestQueue depth pattern) and carries the
``kv.alloc`` fault site so the chaos harness can simulate exhaustion
deterministically.

Elastic capacity (ISSUE 15): :meth:`~KVBlockPool.shrink` parks free
blocks as *spare* (non-allocatable) capacity and
:meth:`~KVBlockPool.grow` returns them to service — the autoscaler's
KV actuator, riding the ``kv_pool.resize`` fault site. Spare is pure
host-side admission bookkeeping (the device pool array never moves);
shrink refuses to cut the free list below the worst single-admission
need ever recorded by :meth:`~KVBlockPool.record_deferral`, so parked
capacity can never starve the largest request the pool has seen.

Quantized layouts (ROADMAP item 3): the pool's DEVICE storage
(:func:`~sparkdl_tpu.models.gpt.init_block_pool`) can hold blocks in
``bf16`` or ``int8`` (one fp32 scale per written column) instead of the
compute dtype — :data:`KV_DTYPES`. This class stays dtype-agnostic
bookkeeping; it records the layout for observability
(``sparkdl_kv_pool_dtype{dtype=...}`` counts live pools per layout) and
:func:`kv_bytes_per_token` / :func:`kv_capacity_ratio` give the sizing
arithmetic benches and admission math share: int8 fits 2-4x the live
tokens of fp32 in the same pool bytes, which is directly more
concurrent users per chip.
"""

from __future__ import annotations

import collections
from typing import Iterable, Optional

from sparkdl_tpu.observability.registry import GaugeShare, registry

_M_TOTAL = registry().gauge(
    "sparkdl_kv_blocks_total",
    "KV pool capacity in blocks, all pools")
_M_USED = registry().gauge(
    "sparkdl_kv_blocks_used",
    "allocated KV blocks (live slots + cached prefixes), all pools")
_M_DEFERRED = registry().counter(
    "sparkdl_kv_admission_deferred_total",
    "admissions re-queued because the KV block pool was exhausted")
_M_SPARE = registry().gauge(
    "sparkdl_kv_blocks_spare",
    "KV blocks parked as spare (non-allocatable) capacity by the "
    "autoscaler, all pools")
_M_DTYPE = registry().gauge(
    "sparkdl_kv_pool_dtype",
    "live KV block pools by storage layout", labels=("dtype",))
_M_SP_IMBALANCE = registry().gauge(
    "sparkdl_sp_shard_imbalance",
    "sequence-sharded pool imbalance: (max - min) used blocks across "
    "sp shards / blocks per shard (0 = perfectly balanced)")

#: Supported pool storage layouts: "fp32" stores at the model's compute
#: dtype (exact, the default), "bf16"/"int8" compress the resident pool
#: (compute still runs at the model dtype; see models.gpt.quantize_kv).
KV_DTYPES = ("fp32", "bf16", "int8")

_KV_ITEMSIZE = {"bf16": 2, "int8": 1}


def kv_bytes_per_token(config, dtype: str = "fp32") -> int:
    """Resident pool bytes one cached token costs under ``dtype``:
    K + V columns across every layer, plus (int8) the two per-column
    fp32 scales. Pure arithmetic — the number benches assert capacity
    ratios with and operators size pools by. The ``"fp32"`` layout
    stores at the MODEL's compute dtype (``config.dtype``, usually
    float32), so a bf16-compute model honestly reports the native
    layout at 2 bytes/element — and near-zero gain from the "bf16"
    layout."""
    import numpy as np

    if dtype not in KV_DTYPES:
        raise ValueError(
            f"unknown KV dtype {dtype!r} (one of {KV_DTYPES})")
    item = (np.dtype(config.dtype).itemsize if dtype == "fp32"
            else _KV_ITEMSIZE[dtype])
    hd = config.hidden_size // config.num_heads
    per_layer = 2 * config.num_heads * hd * item
    if dtype == "int8":
        per_layer += 2 * 4  # k_scale + v_scale, fp32, one per column
    return config.num_layers * per_layer


def kv_capacity_ratio(config, dtype: str) -> float:
    """How many live tokens ``dtype`` fits per NATIVE-layout token in
    the same pool bytes (>= 2.0 for int8 at every real model width
    when compute is float32; ~2x from bf16 compute)."""
    return (kv_bytes_per_token(config, "fp32")
            / kv_bytes_per_token(config, dtype))


class KVBlockPool:
    """Free list + refcounts over ``n_blocks`` physical KV blocks.

    ``allocate`` hands out refcount-1 block ids (or None — the caller
    defers); ``ref``/``deref`` track sharing; a block whose refcount
    hits zero is NOT auto-freed — the caller (the prefix cache) decides
    whether it goes back to the free list (:meth:`release`) or stays
    resident as an evictable cached prefix. ``sentinel`` (== n_blocks,
    one past the last valid id) marks empty block-table entries: the
    device-side gather clips it and the scatter drops it, so an
    unoccupied table entry can never read or corrupt a live block.
    """

    def __init__(self, n_blocks: int, block_size: int,
                 dtype: str = "fp32"):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        if dtype not in KV_DTYPES:
            raise ValueError(
                f"unknown KV dtype {dtype!r} (one of {KV_DTYPES})")
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.dtype = dtype
        self._free: "collections.deque[int]" = collections.deque(
            range(n_blocks))
        self._is_free = [True] * n_blocks
        self._ref = [0] * n_blocks
        #: high-water mark of :attr:`used_count` — the number that sizes
        #: a pool (end-of-run used_count has already fallen back to the
        #: cached-prefix residual)
        self.used_peak = 0
        #: consecutive deferrals (:meth:`record_deferral`) with no
        #: intervening recovery — the signal /healthz reads as degraded.
        #: A :meth:`release` that frees ENOUGH blocks to cover the
        #: deferred need clears it (the pressure is over the moment
        #: capacity exists, not only at the next successful admission),
        #: as does the engine on admission.
        self.deferral_streak = 0
        #: worst-case blocks the most recent deferral was short — the
        #: bar a release must clear to end the episode (1 when the
        #: caller never said: any free block counts)
        self._deferred_need = 1
        #: worst-case single-admission need EVER recorded — the floor
        #: :meth:`shrink` must keep free (ISSUE 15: spare capacity can
        #: never starve the largest request this pool has seen defer)
        self.need_peak = 1
        #: blocks parked as spare capacity by the autoscaler: off the
        #: free list, never allocatable, not "used" either — grow()
        #: returns them to service (the device pool array is untouched;
        #: spare is host-side admission bookkeeping)
        self._spare: "list[int]" = []
        #: free blocks the host tier expects to claim for unparks
        #: (ROADMAP item 1): parked sessions resume with one block
        #: allocation per parked block, so :meth:`shrink` must leave
        #: this many free on top of :attr:`need_peak` or scale-down
        #: strands resumes behind re-prefills. Maintained by the
        #: engine under its lock (0 when tiering is off).
        self.unpark_reserved = 0
        self._closed = False
        self._g_total = GaugeShare(_M_TOTAL)
        self._g_used = GaugeShare(_M_USED)
        self._g_spare = GaugeShare(_M_SPARE)
        self._g_dtype = GaugeShare(_M_DTYPE.labels(dtype=dtype))
        self._g_total.set(n_blocks)
        self._g_used.set(0)
        self._g_spare.set(0)
        self._g_dtype.set(1)

    # -- introspection -------------------------------------------------------
    @property
    def sentinel(self) -> int:
        """Block-table id meaning "no block": gather clips, scatter drops."""
        return self.n_blocks

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def spare_count(self) -> int:
        """Blocks parked out of service by the autoscaler."""
        return len(self._spare)

    @property
    def serving_count(self) -> int:
        """Blocks in service (allocatable or allocated): physical
        capacity minus spare."""
        return self.n_blocks - len(self._spare)

    @property
    def used_count(self) -> int:
        """Blocks holding data: live slots + cached prefixes (spare
        blocks are neither free nor used)."""
        return self.n_blocks - self.free_count - len(self._spare)

    def refcount(self, block_id: int) -> int:
        return self._ref[block_id]

    # -- allocation ----------------------------------------------------------
    def allocate(self, n: int) -> "Optional[list[int]]":
        """Pop ``n`` blocks at refcount 1, or None when the free list is
        short (the caller defers — pool exhaustion is backpressure, not
        an error). ``kv.alloc`` is a fault site: an armed plan makes
        exhaustion injectable for the chaos harness."""
        from sparkdl_tpu.reliability.faults import fault_point

        fault_point("kv.alloc")
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > self.free_count:
            return None
        out = [self._pop_block() for _ in range(n)]
        for bid in out:
            self._ref[bid] = 1
            self._is_free[bid] = False
        self._update_gauges()
        return out

    def _pop_block(self) -> int:
        """Take one free block (subclass hook, the mirror of
        :meth:`_free_block` — the sharded pool pops round-robin across
        its shard stripes). Only called with ``free_count`` cover."""
        return self._free.popleft()

    def ref(self, block_ids: Iterable[int]) -> None:
        """Add one reference per id. Refcount 0 is legal here — that is
        a CACHED block (off the free list, trie-registered) being
        resurrected by a prefix match; only free-list blocks reject."""
        for bid in block_ids:
            if self._is_free[bid]:
                raise RuntimeError(
                    f"ref of free block {bid}: allocator bookkeeping "
                    "corrupt"
                )
            self._ref[bid] += 1

    def deref(self, block_ids: Iterable[int]) -> "list[int]":
        """Drop one reference per id; returns the ids that hit zero (the
        caller frees or keeps them as cached prefixes)."""
        zeroed = []
        for bid in block_ids:
            if self._ref[bid] < 1:
                raise RuntimeError(
                    f"deref of free block {bid}: double release"
                )
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                zeroed.append(bid)
        return zeroed

    def release(self, block_ids: Iterable[int]) -> None:
        """Return refcount-0 blocks to the free list. Freeing enough
        capacity to cover the deferred need ends the exhaustion
        episode: the deferral streak resets HERE, so /healthz degraded
        state self-clears the moment a retiring slot makes the pool
        healthy again — not only when the next admission succeeds (an
        idle engine with no queued work would otherwise read degraded
        forever). A free that does NOT cover the need keeps the streak:
        a large request starving behind small-block churn must still
        read degraded and still reach its postmortem trigger."""
        freed = 0
        for bid in block_ids:
            if self._ref[bid] != 0:
                raise RuntimeError(
                    f"release of block {bid} at refcount "
                    f"{self._ref[bid]}: still referenced"
                )
            if self._is_free[bid]:
                raise RuntimeError(f"double free of block {bid}")
            self._free_block(bid)
            self._is_free[bid] = True
            freed += 1
        if freed and self.free_count >= self._deferred_need:
            self.deferral_streak = 0
        self._update_gauges()

    def _free_block(self, bid: int) -> None:
        """Return one block to the free structure (subclass hook —
        the sharded pool files it under its shard's stripe)."""
        self._free.append(bid)

    def record_deferral(self, need: "int | None" = None) -> None:
        """Count one deferral; ``need`` is the worst-case block count
        the deferred admission was asking for (sets the recovery bar
        :meth:`release` must clear)."""
        _M_DEFERRED.inc()
        self.deferral_streak += 1
        if need is not None:
            self._deferred_need = max(1, need)
            self.need_peak = max(self.need_peak, self._deferred_need)

    def reset_deferral_streak(self) -> None:
        """An admission succeeded (or the queue drained past the
        pressure): the exhaustion episode is over."""
        self.deferral_streak = 0

    # -- serving <-> spare resize (ISSUE 15: the autoscaler's actuator) ------
    def grow(self, n: int) -> int:
        """Return up to ``n`` spare blocks to the serving free list
        (scale-up on deferral streaks). Returns the blocks actually
        moved. The caller holds whatever lock guards allocation (the
        engine lock) — same single-owner contract as every other
        method here. ``kv_pool.resize`` is a fault site: an injected
        fault aborts the move before any bookkeeping changes, so the
        autoscaler defers the decision."""
        from sparkdl_tpu.reliability.faults import fault_point

        fault_point("kv_pool.resize")
        if n < 0:
            raise ValueError(f"cannot grow by {n} blocks")
        moved = min(n, len(self._spare))
        for _ in range(moved):
            self._return_spare_block(self._spare.pop())
        if moved and self.free_count >= self._deferred_need:
            # capacity now covers the deferred need: the exhaustion
            # episode ends exactly as a covering release() would end it
            self.deferral_streak = 0
        self._update_gauges()
        return moved

    def shrink(self, n: int) -> int:
        """Park up to ``n`` FREE blocks as spare capacity (scale-down).
        Guard: the free list is never shrunk below the worst
        single-admission need this pool ever recorded
        (:attr:`need_peak`, fed by :meth:`record_deferral`) *plus* the
        host tier's :attr:`unpark_reserved` — spare capacity must not
        manufacture the exhaustion it exists to absorb, nor strand a
        parked session's resume behind a re-prefill. Returns the
        blocks actually moved (possibly 0)."""
        from sparkdl_tpu.reliability.faults import fault_point

        fault_point("kv_pool.resize")
        if n < 0:
            raise ValueError(f"cannot shrink by {n} blocks")
        allowance = (self.free_count
                     - max(self._deferred_need, self.need_peak)
                     - self.unpark_reserved)
        moved = max(0, min(n, allowance))
        for _ in range(moved):
            self._spare.append(self._take_free_block())
        self._update_gauges()
        return moved

    def _take_free_block(self) -> int:
        """Remove one block from the free structure for parking
        (subclass hook, mirror of :meth:`_return_spare_block`). Only
        called with ``free_count`` cover."""
        return self._free.pop()

    def _return_spare_block(self, bid: int) -> None:
        """Put one parked block back on the free structure (subclass
        hook). Unlike :meth:`_free_block` this must NOT touch used
        accounting — a spare block was never used."""
        self._free.append(bid)

    def _update_gauges(self) -> None:
        used = self.used_count
        if used > self.used_peak:
            self.used_peak = used
        self._g_used.set(used)
        # re-assert capacity + dtype too: a registry().reset() mid-life
        # (test isolation) zeroes the gauges, and values only pushed at
        # construction would stay 0 while used recovers
        self._g_total.set(0 if self._closed else self.n_blocks)
        self._g_spare.set(0 if self._closed else len(self._spare))
        self._g_dtype.set(0 if self._closed else 1)

    def close(self) -> None:
        """Retract this pool's gauge contributions (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._g_total.set(0)
        self._g_used.set(0)
        self._g_spare.set(0)
        self._g_dtype.set(0)


class SeqShardedBlockPool(KVBlockPool):
    """A :class:`KVBlockPool` whose physical blocks live sequence-sharded
    across ``sp`` chips (ISSUE 13 / ROADMAP item 2).

    The device pool array ``[layers, n_blocks, block_size, H, D]`` is
    placed with its block axis on the ``sp`` mesh axis (contiguous
    shards: chip ``c`` holds blocks
    ``[c * blocks_per_shard, (c+1) * blocks_per_shard)``), so a long
    context's resident KV never has to fit one chip — the table maps a
    VIRTUAL block id to ``(chip, local block)`` via :meth:`shard_of` /
    :meth:`local_id`, exactly the contiguous layout
    :func:`jax.sharding.NamedSharding` gives ``P(None, "sp")``.

    Allocation is **striped**: :meth:`allocate` round-robins across
    per-shard free lists so one sequence's blocks spread over chips
    (consecutive virtual columns land on alternating chips, which is
    what makes the per-chunk head gather an all-to-all instead of one
    hot chip) and no shard exhausts while its peers sit idle. The
    ``sparkdl_sp_shard_imbalance`` gauge publishes
    ``(max - min) used blocks across shards / blocks_per_shard`` so an
    operator can see striping degrade (e.g. a workload of exactly
    shard-sized sequences). Refcounts, deferral streaks, and the free /
    release contracts are the base class's — sharing (COW, prefix
    reuse) works across shards because block ids stay virtual
    everywhere above the device layout.
    """

    def __init__(self, n_blocks: int, block_size: int, sp: int,
                 dtype: str = "fp32"):
        if sp < 1:
            raise ValueError(f"sp must be >= 1, got {sp}")
        if n_blocks % sp:
            raise ValueError(
                f"n_blocks {n_blocks} not divisible by sp={sp}: the "
                "device pool shards its block axis evenly across chips")
        super().__init__(n_blocks, block_size, dtype=dtype)
        self.sp = sp
        self.blocks_per_shard = n_blocks // sp
        # striped per-shard free lists REPLACE the base deque (cleared
        # below so no stale membership survives); _is_free stays the
        # authoritative free-ness record, and per-shard used counters
        # are maintained incrementally — every pool operation stays
        # O(allocated blocks), never O(n_blocks)
        self._free.clear()
        self._shard_free: "list[collections.deque[int]]" = [
            collections.deque(range(s * self.blocks_per_shard,
                                    (s + 1) * self.blocks_per_shard))
            for s in range(sp)
        ]
        self._shard_used = [0] * sp
        self._next_shard = 0
        # imbalance rides GaugeShare like every other gauge here:
        # concurrent pools SUM their contributions (one pool — the
        # common case — reads exactly its own skew) and close()
        # retracts this pool's share. Materialize the zero sample up
        # front: GaugeShare only writes on CHANGE, so a pool that stays
        # perfectly balanced would otherwise never create the series and
        # the family's presence in snapshots (a bench-contract assert)
        # would depend on runtime allocation skew.
        _M_SP_IMBALANCE.inc(0.0)
        self._g_imb = GaugeShare(_M_SP_IMBALANCE)
        self._update_imbalance()

    # -- virtual id -> device placement --------------------------------------
    def shard_of(self, block_id: int) -> int:
        """Which sp chip holds this virtual block."""
        return block_id // self.blocks_per_shard

    def local_id(self, block_id: int) -> int:
        """The block's index within its chip's shard."""
        return block_id % self.blocks_per_shard

    def shard_used_counts(self) -> "list[int]":
        """Used (off-free-list) blocks per shard, virtual-order."""
        return list(self._shard_used)

    @property
    def free_count(self) -> int:
        return sum(len(d) for d in self._shard_free)

    # -- striped allocation ---------------------------------------------------
    def _pop_block(self) -> int:
        # round-robin across shards from the stripe cursor (the base
        # allocate guarantees free_count cover, so a non-empty shard
        # exists) — allocation contract, fault site, and gauges are the
        # base class's; only the pop ORDER changes
        while True:
            shard = self._next_shard % self.sp
            self._next_shard += 1
            if self._shard_free[shard]:
                self._shard_used[shard] += 1
                return self._shard_free[shard].popleft()

    def _free_block(self, bid: int) -> None:
        shard = self.shard_of(bid)
        self._shard_free[shard].append(bid)
        self._shard_used[shard] -= 1

    def _take_free_block(self) -> int:
        # park from the shard with the MOST free blocks: spare capacity
        # drains evenly off the stripes instead of exhausting one chip
        # (spare blocks are neither free nor used — shard_used untouched)
        shard = max(range(self.sp),
                    key=lambda s: len(self._shard_free[s]))
        return self._shard_free[shard].pop()

    def _return_spare_block(self, bid: int) -> None:
        self._shard_free[self.shard_of(bid)].append(bid)

    def _update_gauges(self) -> None:
        super()._update_gauges()
        self._update_imbalance()

    def _update_imbalance(self) -> None:
        if getattr(self, "blocks_per_shard", 0):
            used = self._shard_used
            self._g_imb.set(
                0.0 if self._closed
                else (max(used) - min(used)) / self.blocks_per_shard)

    def close(self) -> None:
        if self._closed:
            return
        super().close()
        self._g_imb.set(0.0)
