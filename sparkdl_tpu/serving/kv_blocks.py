"""Block-paged KV allocation for continuous GPT serving.

The dense continuous engine holds one ``[layers, n_slots, max_len, H, D]``
cache, so its memory contract is ``n_slots x max_len`` worst-case columns
whether or not tokens exist. This module is the host-side half of the
paged layout (ROADMAP item 4, the vLLM idea): the device holds one
``[layers, n_blocks, block_size, H, D]`` pool
(:func:`~sparkdl_tpu.models.gpt.init_block_pool`), each serving slot maps
its logical columns onto pool blocks through a per-slot block table, and
THIS class owns the free list and refcounts — so

* capacity is bounded by live tokens (``blocks_used x block_size``), not
  by ``n_slots x max_len``;
* a physical block can back many slots at once (refcounted — how
  :mod:`~sparkdl_tpu.serving.prefix_cache` shares prompt prefixes);
* admission against an exhausted pool *defers* (the engine re-queues the
  request and retries as slots retire) instead of erroring.

Bookkeeping is plain Python under the engine lock — allocation is a
host-side scheduling decision, never device work. The pool publishes
``sparkdl_kv_blocks_total`` / ``sparkdl_kv_blocks_used`` gauges as
delta contributions (several pools may live in one process; each adds
its share instead of clobbering the others — the RequestQueue depth
pattern) and carries the ``kv.alloc`` fault site so the chaos harness
can simulate exhaustion deterministically.
"""

from __future__ import annotations

import collections
from typing import Iterable, Optional

from sparkdl_tpu.observability.registry import GaugeShare, registry

_M_TOTAL = registry().gauge(
    "sparkdl_kv_blocks_total",
    "KV pool capacity in blocks, all pools")
_M_USED = registry().gauge(
    "sparkdl_kv_blocks_used",
    "allocated KV blocks (live slots + cached prefixes), all pools")
_M_DEFERRED = registry().counter(
    "sparkdl_kv_admission_deferred_total",
    "admissions re-queued because the KV block pool was exhausted")


class KVBlockPool:
    """Free list + refcounts over ``n_blocks`` physical KV blocks.

    ``allocate`` hands out refcount-1 block ids (or None — the caller
    defers); ``ref``/``deref`` track sharing; a block whose refcount
    hits zero is NOT auto-freed — the caller (the prefix cache) decides
    whether it goes back to the free list (:meth:`release`) or stays
    resident as an evictable cached prefix. ``sentinel`` (== n_blocks,
    one past the last valid id) marks empty block-table entries: the
    device-side gather clips it and the scatter drops it, so an
    unoccupied table entry can never read or corrupt a live block.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.n_blocks = n_blocks
        self.block_size = block_size
        self._free: "collections.deque[int]" = collections.deque(
            range(n_blocks))
        self._is_free = [True] * n_blocks
        self._ref = [0] * n_blocks
        #: high-water mark of :attr:`used_count` — the number that sizes
        #: a pool (end-of-run used_count has already fallen back to the
        #: cached-prefix residual)
        self.used_peak = 0
        self._closed = False
        self._g_total = GaugeShare(_M_TOTAL)
        self._g_used = GaugeShare(_M_USED)
        self._g_total.set(n_blocks)
        self._g_used.set(0)

    # -- introspection -------------------------------------------------------
    @property
    def sentinel(self) -> int:
        """Block-table id meaning "no block": gather clips, scatter drops."""
        return self.n_blocks

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        """Blocks off the free list: live slots + cached prefixes."""
        return self.n_blocks - len(self._free)

    def refcount(self, block_id: int) -> int:
        return self._ref[block_id]

    # -- allocation ----------------------------------------------------------
    def allocate(self, n: int) -> "Optional[list[int]]":
        """Pop ``n`` blocks at refcount 1, or None when the free list is
        short (the caller defers — pool exhaustion is backpressure, not
        an error). ``kv.alloc`` is a fault site: an armed plan makes
        exhaustion injectable for the chaos harness."""
        from sparkdl_tpu.reliability.faults import fault_point

        fault_point("kv.alloc")
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        out = [self._free.popleft() for _ in range(n)]
        for bid in out:
            self._ref[bid] = 1
            self._is_free[bid] = False
        self._update_gauges()
        return out

    def ref(self, block_ids: Iterable[int]) -> None:
        """Add one reference per id. Refcount 0 is legal here — that is
        a CACHED block (off the free list, trie-registered) being
        resurrected by a prefix match; only free-list blocks reject."""
        for bid in block_ids:
            if self._is_free[bid]:
                raise RuntimeError(
                    f"ref of free block {bid}: allocator bookkeeping "
                    "corrupt"
                )
            self._ref[bid] += 1

    def deref(self, block_ids: Iterable[int]) -> "list[int]":
        """Drop one reference per id; returns the ids that hit zero (the
        caller frees or keeps them as cached prefixes)."""
        zeroed = []
        for bid in block_ids:
            if self._ref[bid] < 1:
                raise RuntimeError(
                    f"deref of free block {bid}: double release"
                )
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                zeroed.append(bid)
        return zeroed

    def release(self, block_ids: Iterable[int]) -> None:
        """Return refcount-0 blocks to the free list."""
        for bid in block_ids:
            if self._ref[bid] != 0:
                raise RuntimeError(
                    f"release of block {bid} at refcount "
                    f"{self._ref[bid]}: still referenced"
                )
            if self._is_free[bid]:
                raise RuntimeError(f"double free of block {bid}")
            self._free.append(bid)
            self._is_free[bid] = True
        self._update_gauges()

    def record_deferral(self) -> None:
        _M_DEFERRED.inc()

    def _update_gauges(self) -> None:
        used = self.used_count
        if used > self.used_peak:
            self.used_peak = used
        self._g_used.set(used)
        # re-assert capacity too: a registry().reset() mid-life (test
        # isolation) zeroes the gauge, and a total that is only pushed
        # at construction would stay 0 while used recovers
        self._g_total.set(0 if self._closed else self.n_blocks)

    def close(self) -> None:
        """Retract this pool's gauge contributions (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._g_total.set(0)
        self._g_used.set(0)
