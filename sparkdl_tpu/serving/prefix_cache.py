"""Radix-style prefix cache over paged KV blocks.

Chat/RAG traffic shares prompt prefixes (system prompts, retrieved
documents): the K/V a prefill computes for those tokens is a pure
function of the token ids before them, so a second request with the
same prefix can reuse the first one's blocks and prefill only its
suffix — prefill FLOPs drop in proportion to the hit rate, the dominant
serving win for shared-prompt traffic (ROADMAP item 4).

Structure: a trie keyed by **full-block token tuples** (``block_size``
tokens per edge), so a path from the root spells out an exact token
prefix and each node owns the physical block holding that span's K/V.
A node additionally carries *partial entries* — tail blocks whose
prompt filled only ``q < block_size`` slots — which are shared by
**copy-on-write**: a matching request gathers the partial block's
content into its own private prefill cache and re-installs it into a
block IT owns, so the donor (possibly still decoding into that very
block past offset ``q``) is never written by a sibling.

Lifetime: matched blocks are refcounted through
:class:`~sparkdl_tpu.serving.kv_blocks.KVBlockPool`; a registered block
whose refcount drops to zero stays resident as an evictable cache entry
rather than returning to the free list. Eviction is LRU over
refcount-zero **leaves** (evicting a parent before its children would
leave an unmatchable dangling suffix), invoked by the engine when
allocation comes up short. Unregistered blocks free immediately at
refcount zero.

All bookkeeping runs under the engine lock — host-side scheduling,
no device work. Spine metrics: ``sparkdl_prefix_hits_total`` /
``sparkdl_prefix_misses_total`` count prompt TOKENS served from cache
vs prefilled, ``sparkdl_prefix_evictions_total`` counts blocks evicted.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
from typing import Any, Optional

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.serving.kv_blocks import KVBlockPool

_M_HITS = registry().counter(
    "sparkdl_prefix_hits_total",
    "prompt tokens served from cached KV prefixes (prefill skipped)")
_M_MISSES = registry().counter(
    "sparkdl_prefix_misses_total",
    "prompt tokens prefilled from scratch")
_M_EVICTIONS = registry().counter(
    "sparkdl_prefix_evictions_total",
    "cached prefix blocks evicted (LRU, refcount-0 leaves)")


#: chain_hash root: the hash of the empty prefix (any fixed value works;
#: it only needs to agree across hosts, which a constant guarantees)
DIGEST_ROOT = 0


def chain_hash(parent: int, tokens: "tuple[int, ...]") -> int:
    """Stable hash of one more block of prefix tokens chained onto the
    parent prefix's hash — the prefix→host digest entry (ISSUE 14).

    Chaining makes hashing a prompt's every block-aligned prefix O(L)
    instead of O(L²/bs), and ``blake2b`` (not Python ``hash``) keeps the
    value identical across processes and hosts regardless of
    ``PYTHONHASHSEED`` — the property that lets a router compare a local
    prompt's hashes against digests other hosts published."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent).to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return int.from_bytes(h.digest(), "little")


@dataclasses.dataclass
class _Partial:
    """A cached tail block holding ``len(tokens) < block_size`` prompt
    tokens (shared copy-on-write, never in a sharer's block table)."""

    tokens: tuple
    block_id: int
    parent: Any
    stamp: int


class _Node:
    """One full cached block: ``key`` is its ``block_size``-token span,
    the root-to-node path spells the whole prefix."""

    __slots__ = ("key", "block_id", "parent", "children", "partials",
                 "stamp")

    def __init__(self, key, block_id, parent, stamp):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: "dict[tuple, _Node]" = {}
        self.partials: "list[_Partial]" = []
        self.stamp = stamp


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """Longest cached prefix of one prompt. ``full_blocks`` go straight
    into the slot's block table (shared, read-only — decode never
    writes columns below the prompt length); ``partial_block`` is
    gathered then re-installed copy-on-write. All matched blocks are
    already refcounted; release through :meth:`PrefixCache.release`
    (full) and a single release of the partial once copied."""

    full_blocks: "list[int]"
    partial_block: "Optional[int]"
    partial_tokens: int
    hit_tokens: int


class PrefixCache:
    """Token-trie prefix index over a :class:`KVBlockPool`."""

    def __init__(self, pool: KVBlockPool):
        self.pool = pool
        self.block_size = pool.block_size
        self._clock = itertools.count(1)
        self._root = _Node(None, -1, None, 0)
        #: block_id -> _Node | _Partial for every trie-registered block
        self._registered: "dict[int, Any]" = {}
        # engine-visible counters (the registry families are process
        # totals; benches/snapshots want this engine's share)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0

    # -- lookup --------------------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        return len(self._registered)

    def match(self, tokens: "tuple[int, ...]") -> PrefixMatch:
        """Longest cached prefix of ``tokens``; increfs every matched
        block so concurrent eviction cannot reclaim it before the
        caller installs/copies. Callers pass the prompt MINUS its last
        token: the token feeding the first decode step must always be
        prefilled, because the cache holds K/V, not logits."""
        bs = self.block_size
        node = self._root
        full: "list[int]" = []
        i = 0
        while len(tokens) - i >= bs:
            child = node.children.get(tokens[i:i + bs])
            if child is None:
                break
            full.append(child.block_id)
            node = child
            node.stamp = next(self._clock)
            i += bs
        best: "Optional[_Partial]" = None
        best_q = 0
        rest = tokens[i:]
        for p in node.partials:
            q = _common_prefix(p.tokens, rest)
            if q > best_q:
                best, best_q = p, q
        self.pool.ref(full)
        partial_id = None
        if best is not None and best_q > 0:
            partial_id = best.block_id
            self.pool.ref([partial_id])
            best.stamp = next(self._clock)
        return PrefixMatch(full, partial_id, best_q, i + best_q)

    def suggest(self, tokens: "tuple[int, ...]", k: int) -> "list[int]":
        """Draft up to ``k`` tokens that FOLLOWED this exact context in
        a cached prompt — the zero-weight draft source for speculative
        decoding (ROADMAP item 3): the trie already spells out every
        prompt it has seen, so when one request's context is a prefix
        of a cached longer prompt, the cached continuation is a high-
        probability draft (chat history growing turn by turn, retrieval
        prompts sharing scaffolding).

        Token ids only — no block references, no refcounts, no stamps
        touched: drafting must never keep a block alive or perturb LRU
        order (a wrong draft costs one rejected verify position, not a
        corrupted cache).
        """
        if k < 1:
            return []
        bs = self.block_size
        node = self._root
        i = 0
        while len(tokens) - i >= bs:
            child = node.children.get(tokens[i:i + bs])
            if child is None:
                break
            node = child
            i += bs
        rest = tokens[i:]
        out: "list[int]" = []
        # descend through the child whose key extends the remainder;
        # exact-boundary contexts (rest empty) continue down the most
        # recently used child path
        while len(out) < k:
            step = None
            best_stamp = -1
            for key, child in node.children.items():
                if key[:len(rest)] == rest and child.stamp > best_stamp:
                    step, best_stamp = child, child.stamp
            if step is not None:
                out.extend(step.key[len(rest):])
                node, rest = step, ()
                continue
            # no full-block continuation: the freshest partial tail
            # extending the remainder ends the walk
            best = None
            for p in node.partials:
                if (len(p.tokens) > len(rest)
                        and p.tokens[:len(rest)] == rest
                        and (best is None or p.stamp > best.stamp)):
                    best = p
            if best is not None:
                out.extend(best.tokens[len(rest):])
            break
        return out[:k]

    def block_hashes(self, max_entries: int = 1024) -> "list[int]":
        """Chained :func:`chain_hash` values of the cached block-aligned
        prefixes — the compact digest a host publishes so a router can
        place requests where their prefix blocks already live
        (ISSUE 14). Most-recently-used first, capped at ``max_entries``
        (a bounded digest stays cheap to ship and compare; evicting the
        coldest entries first mirrors what the LRU eviction would drop
        anyway). Partial tail blocks are excluded: the digest is
        block-aligned by construction, matching the router-side
        :func:`~sparkdl_tpu.fabric.digest.prompt_block_hashes` grid.
        Call under the engine lock (same discipline as every other trie
        walk)."""
        if max_entries < 1:
            return []
        entries: "list[tuple[int, int]]" = []
        stack: "list[tuple[_Node, int]]" = [
            (child, chain_hash(DIGEST_ROOT, key))
            for key, child in self._root.children.items()
        ]
        while stack:
            node, h = stack.pop()
            entries.append((node.stamp, h))
            for key, child in node.children.items():
                stack.append((child, chain_hash(h, key)))
        entries.sort(reverse=True)
        return [h for _, h in entries[:max_entries]]

    def record_lookup(self, hit_tokens: int, miss_tokens: int) -> None:
        """Land one admission's hit/miss split (prompt tokens) in the
        spine + the engine-local counters."""
        if hit_tokens:
            _M_HITS.inc(hit_tokens)
            self.hit_tokens += hit_tokens
        if miss_tokens:
            _M_MISSES.inc(miss_tokens)
            self.miss_tokens += miss_tokens

    # -- registration --------------------------------------------------------
    def register(self, tokens: "tuple[int, ...]",
                 block_ids: "list[int]") -> None:
        """Index a freshly prefilled prompt: ``block_ids[i]`` holds
        tokens ``[i*bs, (i+1)*bs)`` (the slot's table prefix — shared
        blocks walk existing nodes, owned blocks become new entries).
        A registered block survives refcount zero as an evictable
        cache entry instead of freeing."""
        bs = self.block_size
        node = self._root
        n_full = len(tokens) // bs
        for i in range(n_full):
            key = tokens[i * bs:(i + 1) * bs]
            child = node.children.get(key)
            if child is None:
                bid = block_ids[i]
                child = _Node(key, bid, node, next(self._clock))
                node.children[key] = child
                self._registered[bid] = child
            node = child
            node.stamp = next(self._clock)
        tail = tokens[n_full * bs:]
        if tail:
            bid = block_ids[n_full]
            if bid not in self._registered and not any(
                    p.tokens == tail for p in node.partials):
                p = _Partial(tail, bid, node, next(self._clock))
                node.partials.append(p)
                self._registered[bid] = p

    # -- release / eviction --------------------------------------------------
    def release(self, block_ids: "list[int]") -> None:
        """Drop one reference per block; zero-ref blocks return to the
        free list unless trie-registered (those stay cached until
        evicted)."""
        free_now = [bid for bid in self.pool.deref(block_ids)
                    if bid not in self._registered]
        if free_now:
            self.pool.release(free_now)

    def _evictable(self, bid: int, entry: Any) -> bool:
        if self.pool.refcount(bid) != 0:
            return False
        if isinstance(entry, _Node) and (entry.children
                                         or entry.partials):
            return False  # interior node: children would dangle
        return True

    def evict(self, n: int) -> int:
        """Free up to ``n`` cached blocks, LRU over refcount-0 leaves;
        returns how many were freed. Evicting a leaf may expose its
        parent as the next candidate, so pressure drains whole cold
        paths tail-first. One candidate pass + a stamp heap: O(cached +
        n log cached), not a full rescan per freed block — this runs
        under the engine lock on the admission path."""
        import heapq

        heap = [(entry.stamp, bid)
                for bid, entry in self._registered.items()
                if self._evictable(bid, entry)]
        heapq.heapify(heap)
        freed = 0
        while freed < n and heap:
            stamp, bid = heapq.heappop(heap)
            entry = self._registered.get(bid)
            if entry is None or not self._evictable(bid, entry):
                continue  # resurrected by a match since the pass
            if entry.stamp != stamp:
                # touched since queued: re-queue at its fresh stamp so
                # LRU order stays honest (stamps only grow: terminates)
                heapq.heappush(heap, (entry.stamp, bid))
                continue
            parent = entry.parent
            if isinstance(entry, _Partial):
                parent.partials.remove(entry)
            else:
                del parent.children[entry.key]
            del self._registered[bid]
            self.pool.release([bid])
            _M_EVICTIONS.inc()
            self.evictions += 1
            freed += 1
            # the eviction may have exposed its parent as a new leaf
            if (parent is not self._root
                    and parent.block_id in self._registered
                    and self._evictable(parent.block_id, parent)):
                heapq.heappush(heap, (parent.stamp, parent.block_id))
        return freed


def _common_prefix(a: tuple, b: tuple) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n
