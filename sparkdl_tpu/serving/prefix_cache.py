"""Radix-style prefix cache over paged KV blocks.

Chat/RAG traffic shares prompt prefixes (system prompts, retrieved
documents): the K/V a prefill computes for those tokens is a pure
function of the token ids before them, so a second request with the
same prefix can reuse the first one's blocks and prefill only its
suffix — prefill FLOPs drop in proportion to the hit rate, the dominant
serving win for shared-prompt traffic (ROADMAP item 4).

Structure: a trie keyed by **full-block token tuples** (``block_size``
tokens per edge), so a path from the root spells out an exact token
prefix and each node owns the physical block holding that span's K/V.
A node additionally carries *partial entries* — tail blocks whose
prompt filled only ``q < block_size`` slots — which are shared by
**copy-on-write**: a matching request gathers the partial block's
content into its own private prefill cache and re-installs it into a
block IT owns, so the donor (possibly still decoding into that very
block past offset ``q``) is never written by a sibling.

Lifetime: matched blocks are refcounted through
:class:`~sparkdl_tpu.serving.kv_blocks.KVBlockPool`; a registered block
whose refcount drops to zero stays resident as an evictable cache entry
rather than returning to the free list. Eviction is LRU over
refcount-zero **leaves** (evicting a parent before its children would
leave an unmatchable dangling suffix), invoked by the engine when
allocation comes up short. Unregistered blocks free immediately at
refcount zero.

Tiered mode (ROADMAP item 1): when the engine attaches a
:class:`~sparkdl_tpu.serving.kv_tiers.TieredKVStore`, the trie becomes
a **3-level hierarchy**. A node's ``tier`` says where its block lives:
``"device"`` nodes hold a live pool block; ``"host"``/``"disk"`` nodes
are *parked* — their raw block bytes moved to the cheap tier, their
``block_id`` invalid, their trie position (and token key) intact so the
next turn can find them. One eviction policy covers all levels:
:meth:`demote` pages cold device leaves out (device→host, cascading
host→disk, dropping from disk last), refcounted shares and partial-
holding nodes never park, and :meth:`restore_path` pages a parked
prefix back in ahead of :meth:`match` so a turn resume costs one H2D
copy instead of a re-prefill. A parked node's children are always
parked too (children park before parents; restore revives parents
before children), which is what makes dropping a parked subtree safe.

All bookkeeping runs under the engine lock — host-side scheduling,
no device work. Spine metrics: ``sparkdl_prefix_hits_total`` /
``sparkdl_prefix_misses_total`` count prompt TOKENS served from cache
vs prefilled, ``sparkdl_prefix_evictions_total`` counts blocks evicted.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

from sparkdl_tpu.observability.registry import registry
from sparkdl_tpu.serving.kv_blocks import KVBlockPool
from sparkdl_tpu.serving.kv_tiers import TieredKVStore

_M_HITS = registry().counter(
    "sparkdl_prefix_hits_total",
    "prompt tokens served from cached KV prefixes (prefill skipped)")
_M_MISSES = registry().counter(
    "sparkdl_prefix_misses_total",
    "prompt tokens prefilled from scratch")
_M_EVICTIONS = registry().counter(
    "sparkdl_prefix_evictions_total",
    "cached prefix blocks evicted (LRU, refcount-0 leaves)")


#: chain_hash root: the hash of the empty prefix (any fixed value works;
#: it only needs to agree across hosts, which a constant guarantees)
DIGEST_ROOT = 0


def chain_hash(parent: int, tokens: "tuple[int, ...]") -> int:
    """Stable hash of one more block of prefix tokens chained onto the
    parent prefix's hash — the prefix→host digest entry (ISSUE 14).

    Chaining makes hashing a prompt's every block-aligned prefix O(L)
    instead of O(L²/bs), and ``blake2b`` (not Python ``hash``) keeps the
    value identical across processes and hosts regardless of
    ``PYTHONHASHSEED`` — the property that lets a router compare a local
    prompt's hashes against digests other hosts published."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent).to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return int.from_bytes(h.digest(), "little")


@dataclasses.dataclass
class _Partial:
    """A cached tail block holding ``len(tokens) < block_size`` prompt
    tokens (shared copy-on-write, never in a sharer's block table)."""

    tokens: tuple
    block_id: int
    parent: Any
    stamp: int


class _Node:
    """One full cached block: ``key`` is its ``block_size``-token span,
    the root-to-node path spells the whole prefix."""

    __slots__ = ("key", "block_id", "parent", "children", "partials",
                 "stamp", "tier", "digest_hash")

    def __init__(self, key, block_id, parent, stamp):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: "dict[tuple, _Node]" = {}
        self.partials: "list[_Partial]" = []
        self.stamp = stamp
        #: "device" | "host" | "disk" — parked nodes keep their trie
        #: position but hold no pool block (block_id is invalid)
        self.tier = "device"
        #: this prefix's chained digest entry, fixed at creation (the
        #: path never changes while the node exists) — what the digest
        #: journal publishes and block_hashes() reads back
        self.digest_hash = (DIGEST_ROOT if parent is None
                            else chain_hash(parent.digest_hash, key))


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """Longest cached prefix of one prompt. ``full_blocks`` go straight
    into the slot's block table (shared, read-only — decode never
    writes columns below the prompt length); ``partial_block`` is
    gathered then re-installed copy-on-write. All matched blocks are
    already refcounted; release through :meth:`PrefixCache.release`
    (full) and a single release of the partial once copied."""

    full_blocks: "list[int]"
    partial_block: "Optional[int]"
    partial_tokens: int
    hit_tokens: int


class PrefixCache:
    """Token-trie prefix index over a :class:`KVBlockPool`."""

    def __init__(self, pool: KVBlockPool,
                 tiers: "Optional[TieredKVStore]" = None,
                 journal_limit: int = 1024):
        self.pool = pool
        self.block_size = pool.block_size
        self._clock = itertools.count(1)
        self._root = _Node(None, -1, None, 0)
        #: block_id -> _Node | _Partial for every trie-registered block
        #: whose bytes are DEVICE-resident (parked nodes leave this map)
        self._registered: "dict[int, Any]" = {}
        #: host/disk tiers for parked nodes (None = flat single-tier)
        self._tiers = tiers
        #: monotonic digest-membership version: bumps once per trie node
        #: added or removed (parking/unparking moves bytes, not
        #: membership, so it does NOT bump). Routers key deltas on it.
        self.digest_version = 0
        #: bounded (version, op, hash) journal of membership mutations —
        #: ``block_hash_delta`` replays the suffix past a router's
        #: version; a router older than the journal's tail gets a gap
        #: (None) and refreshes wholesale. Bounded to the digest cap:
        #: a delta bigger than the digest itself has no reason to exist.
        self._journal: "collections.deque[Tuple[int, str, int]]" = (
            collections.deque(maxlen=max(1, int(journal_limit))))
        # engine-visible counters (the registry families are process
        # totals; benches/snapshots want this engine's share)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0
        self.parks = 0
        self.unparks = 0

    # -- lookup --------------------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        return len(self._registered)

    def match(self, tokens: "tuple[int, ...]") -> PrefixMatch:
        """Longest cached prefix of ``tokens``; increfs every matched
        block so concurrent eviction cannot reclaim it before the
        caller installs/copies. Callers pass the prompt MINUS its last
        token: the token feeding the first decode step must always be
        prefilled, because the cache holds K/V, not logits."""
        bs = self.block_size
        node = self._root
        full: "list[int]" = []
        i = 0
        while len(tokens) - i >= bs:
            child = node.children.get(tokens[i:i + bs])
            if child is None or child.tier != "device":
                # parked child: the bytes are a tier away, not usable
                # as KV — restore_path() runs before match on the
                # tiered admission path, so hitting one here means the
                # restore fell short (re-prefill the rest)
                break
            full.append(child.block_id)
            node = child
            node.stamp = next(self._clock)
            i += bs
        best: "Optional[_Partial]" = None
        best_q = 0
        rest = tokens[i:]
        for p in node.partials:
            q = _common_prefix(p.tokens, rest)
            if q > best_q:
                best, best_q = p, q
        self.pool.ref(full)
        partial_id = None
        if best is not None and best_q > 0:
            partial_id = best.block_id
            self.pool.ref([partial_id])
            best.stamp = next(self._clock)
        return PrefixMatch(full, partial_id, best_q, i + best_q)

    def suggest(self, tokens: "tuple[int, ...]", k: int) -> "list[int]":
        """Draft up to ``k`` tokens that FOLLOWED this exact context in
        a cached prompt — the zero-weight draft source for speculative
        decoding (ROADMAP item 3): the trie already spells out every
        prompt it has seen, so when one request's context is a prefix
        of a cached longer prompt, the cached continuation is a high-
        probability draft (chat history growing turn by turn, retrieval
        prompts sharing scaffolding).

        Token ids only — no block references, no refcounts, no stamps
        touched: drafting must never keep a block alive or perturb LRU
        order (a wrong draft costs one rejected verify position, not a
        corrupted cache).
        """
        if k < 1:
            return []
        bs = self.block_size
        node = self._root
        i = 0
        while len(tokens) - i >= bs:
            child = node.children.get(tokens[i:i + bs])
            if child is None:
                break
            node = child
            i += bs
        rest = tokens[i:]
        out: "list[int]" = []
        # descend through the child whose key extends the remainder;
        # exact-boundary contexts (rest empty) continue down the most
        # recently used child path
        while len(out) < k:
            step = None
            best_stamp = -1
            for key, child in node.children.items():
                if key[:len(rest)] == rest and child.stamp > best_stamp:
                    step, best_stamp = child, child.stamp
            if step is not None:
                out.extend(step.key[len(rest):])
                node, rest = step, ()
                continue
            # no full-block continuation: the freshest partial tail
            # extending the remainder ends the walk
            best = None
            for p in node.partials:
                if (len(p.tokens) > len(rest)
                        and p.tokens[:len(rest)] == rest
                        and (best is None or p.stamp > best.stamp)):
                    best = p
            if best is not None:
                out.extend(best.tokens[len(rest):])
            break
        return out[:k]

    def block_hashes(self, max_entries: int = 1024) -> "list[int]":
        """Chained :func:`chain_hash` values of the cached block-aligned
        prefixes — the compact digest a host publishes so a router can
        place requests where their prefix blocks already live
        (ISSUE 14). Most-recently-used first, capped at ``max_entries``
        (a bounded digest stays cheap to ship and compare; evicting the
        coldest entries first mirrors what the LRU eviction would drop
        anyway). Partial tail blocks are excluded: the digest is
        block-aligned by construction, matching the router-side
        :func:`~sparkdl_tpu.fabric.digest.prompt_block_hashes` grid.
        Call under the engine lock (same discipline as every other trie
        walk)."""
        if max_entries < 1:
            return []
        entries: "list[tuple[int, int]]" = []
        stack: "list[_Node]" = list(self._root.children.values())
        while stack:
            node = stack.pop()
            entries.append((node.stamp, node.digest_hash))
            stack.extend(node.children.values())
        entries.sort(reverse=True)
        return [h for _, h in entries[:max_entries]]

    # -- digest deltas (ISSUE 19) --------------------------------------------
    def _journal_mutation(self, op: str, node: _Node) -> None:
        self.digest_version += 1
        self._journal.append((self.digest_version, op, node.digest_hash))

    def block_hash_delta(self, since_version: int,
                         max_entries: int = 1024) -> "Optional[Dict]":
        """Membership mutations since ``since_version``, coalesced into
        ``added``/``removed`` hash lists — what a router applies on top
        of its last wholesale :meth:`block_hashes` snapshot instead of
        re-shipping the whole digest every refresh (ISSUE 19).

        Returns ``None`` for a **gap**: the journal no longer covers
        ``(since_version, digest_version]`` (the caller fell too far
        behind its bounded tail), the caller claims a future version
        (restarted host), or the coalesced delta would exceed
        ``max_entries`` (wholesale is cheaper at that point). The
        caller answers a gap with a wholesale refresh — always correct,
        never required for correctness (digests are advisory).
        Call under the engine lock, like every other trie walk."""
        since = int(since_version)
        if since > self.digest_version:
            return None  # a future version: the host restarted
        delta = {"since": since, "version": self.digest_version,
                 "added": [], "removed": []}
        if since == self.digest_version:
            return delta  # caught up: the steady-state no-op
        if not self._journal or self._journal[0][0] > since + 1:
            return None  # journal tail truncated past the caller
        added: "set[int]" = set()
        removed: "set[int]" = set()
        for ver, op, h in self._journal:
            if ver <= since:
                continue
            if op == "+":
                removed.discard(h)
                added.add(h)
            else:
                # an add that never reached the caller nets to nothing
                if h in added:
                    added.discard(h)
                else:
                    removed.add(h)
        if len(added) + len(removed) > max_entries:
            return None
        delta["added"] = sorted(added)
        delta["removed"] = sorted(removed)
        return delta

    def record_lookup(self, hit_tokens: int, miss_tokens: int) -> None:
        """Land one admission's hit/miss split (prompt tokens) in the
        spine + the engine-local counters."""
        if hit_tokens:
            _M_HITS.inc(hit_tokens)
            self.hit_tokens += hit_tokens
        if miss_tokens:
            _M_MISSES.inc(miss_tokens)
            self.miss_tokens += miss_tokens

    # -- registration --------------------------------------------------------
    def register(self, tokens: "tuple[int, ...]",
                 block_ids: "list[int]") -> None:
        """Index a freshly prefilled prompt: ``block_ids[i]`` holds
        tokens ``[i*bs, (i+1)*bs)`` (the slot's table prefix — shared
        blocks walk existing nodes, owned blocks become new entries).
        A registered block survives refcount zero as an evictable
        cache entry instead of freeing. Spans whose trie node is
        *parked* are revived in place: the freshly prefilled block
        becomes the node's device block and the stale tier payload is
        dropped (the engine re-prefilled exactly because the bytes were
        a tier away). A block previously indexed as a partial tail that
        has since been decoded full is promoted to a full node, and a
        tail extending an existing partial on the same block grows that
        entry in place (turn-by-turn chat: the session's produced
        tokens become matchable prefix for its next turn)."""
        bs = self.block_size
        node = self._root
        n_full = len(tokens) // bs
        for i in range(n_full):
            key = tokens[i * bs:(i + 1) * bs]
            child = node.children.get(key)
            if child is None or child.tier != "device":
                bid = block_ids[i]
                prev = self._registered.get(bid)
                if isinstance(prev, _Node):
                    break  # block already a full node elsewhere
                if isinstance(prev, _Partial):
                    # decode grew the prompt's tail partial into a full
                    # block: promote (the partial entry would otherwise
                    # alias the same block with fewer tokens)
                    prev.parent.partials.remove(prev)
                    del self._registered[bid]
                if child is None:
                    child = _Node(key, bid, node, next(self._clock))
                    node.children[key] = child
                    self._journal_mutation("+", child)
                else:
                    # parked node, freshly re-prefilled span: revive
                    if self._tiers is not None:
                        self._tiers.drop(child)
                    child.block_id = bid
                    child.tier = "device"
                self._registered[bid] = child
            node = child
            node.stamp = next(self._clock)
        tail = tokens[n_full * bs:]
        if tail:
            bid = block_ids[n_full]
            prev = self._registered.get(bid)
            if (isinstance(prev, _Partial) and prev.parent is node
                    and len(prev.tokens) < len(tail)
                    and tail[:len(prev.tokens)] == prev.tokens):
                prev.tokens = tail
                prev.stamp = next(self._clock)
            elif bid not in self._registered and not any(
                    p.tokens == tail for p in node.partials):
                p = _Partial(tail, bid, node, next(self._clock))
                node.partials.append(p)
                self._registered[bid] = p

    # -- release / eviction --------------------------------------------------
    def release(self, block_ids: "list[int]") -> None:
        """Drop one reference per block; zero-ref blocks return to the
        free list unless trie-registered (those stay cached until
        evicted)."""
        free_now = [bid for bid in self.pool.deref(block_ids)
                    if bid not in self._registered]
        if free_now:
            self.pool.release(free_now)

    def _evictable(self, bid: int, entry: Any) -> bool:
        if self.pool.refcount(bid) != 0:
            return False
        if isinstance(entry, _Node) and (entry.children
                                         or entry.partials):
            return False  # interior node: children would dangle
        return True

    def evict(self, n: int) -> int:
        """Free up to ``n`` cached blocks, LRU over refcount-0 leaves;
        returns how many were freed. Evicting a leaf may expose its
        parent as the next candidate, so pressure drains whole cold
        paths tail-first. One candidate pass + a stamp heap: O(cached +
        n log cached), not a full rescan per freed block — this runs
        under the engine lock on the admission path."""
        import heapq

        heap = [(entry.stamp, bid)
                for bid, entry in self._registered.items()
                if self._evictable(bid, entry)]
        heapq.heapify(heap)
        freed = 0
        while freed < n and heap:
            stamp, bid = heapq.heappop(heap)
            entry = self._registered.get(bid)
            if entry is None or not self._evictable(bid, entry):
                continue  # resurrected by a match since the pass
            if entry.stamp != stamp:
                # touched since queued: re-queue at its fresh stamp so
                # LRU order stays honest (stamps only grow: terminates)
                heapq.heappush(heap, (entry.stamp, bid))
                continue
            parent = entry.parent
            self._evict_entry(bid, entry)
            freed += 1
            # the eviction may have exposed its parent as a new leaf
            if (parent is not self._root
                    and parent.block_id in self._registered
                    and self._evictable(parent.block_id, parent)):
                heapq.heappush(heap, (parent.stamp, parent.block_id))
        return freed

    # -- tiering (ROADMAP item 1) --------------------------------------------
    def _parkable(self, bid: int, entry: Any) -> bool:
        """Device node whose block can page out: refcount zero (shares
        in live block tables never park), no device-tier children
        (children park before parents — the subtree invariant), and no
        partial entries (partials are copy-on-write donors; a reffed
        partial pins its node, a cold one is plain-evicted first)."""
        if not isinstance(entry, _Node) or entry.tier != "device":
            return False
        if self.pool.refcount(bid) != 0:
            return False
        if any(c.tier == "device" for c in entry.children.values()):
            return False
        if entry.partials:
            return False
        return True

    def demote(self, n: int,
               park_payload: "Callable[[int], Optional[Dict]]",
               evict_fallback: bool = True) -> int:
        """Free up to ``n`` device blocks by parking cold leaves into
        the tier store (host, cascading to disk), LRU-first — the
        tiered twin of :meth:`evict` and the single eviction policy of
        the hierarchy: device leaves page DOWN before anything is
        dropped, and only the disk tier's overflow discards state.

        ``park_payload(bid)`` performs the D2H fetch and returns the
        raw block payload, or ``None`` for a torn park (fault injected
        or transfer failure) — those blocks fall back to plain eviction
        when ``evict_fallback`` (re-prefill is always correct).
        Refcount-0 partials interleave in the same LRU order and are
        always plain-evicted (never parked). Returns device blocks
        freed."""
        import heapq

        if self._tiers is None:
            return self.evict(n)
        heap = [(entry.stamp, bid)
                for bid, entry in self._registered.items()
                if (self._parkable(bid, entry)
                    or self._evictable(bid, entry))]
        heapq.heapify(heap)
        freed = 0
        while freed < n and heap:
            stamp, bid = heapq.heappop(heap)
            entry = self._registered.get(bid)
            parkable = entry is not None and self._parkable(bid, entry)
            evictable = entry is not None and self._evictable(bid, entry)
            if not (parkable or evictable):
                continue  # resurrected by a match since queued
            if entry.stamp != stamp:
                heapq.heappush(heap, (entry.stamp, bid))
                continue
            parent = entry.parent
            if parkable:
                payload = park_payload(bid)
                if payload is not None:
                    del self._registered[bid]
                    entry.tier = "host"
                    entry.block_id = -1
                    self.pool.release([bid])
                    self.parks += 1
                    for lost in self._tiers.park(entry, payload):
                        self._prune_parked(lost)
                    freed += 1
                elif evict_fallback and evictable:
                    self._evict_entry(bid, entry)
                    freed += 1
                else:
                    continue  # torn park, not plainly evictable: skip
            else:
                self._evict_entry(bid, entry)
                freed += 1
            # parking/evicting may expose the parent as the next
            # candidate (its last device child / partial just left)
            if (parent is not self._root
                    and parent.block_id in self._registered
                    and (self._parkable(parent.block_id, parent)
                         or self._evictable(parent.block_id, parent))):
                heapq.heappush(heap, (parent.stamp, parent.block_id))
        return freed

    def _evict_entry(self, bid: int, entry: Any) -> None:
        parent = entry.parent
        if isinstance(entry, _Partial):
            parent.partials.remove(entry)
        else:
            del parent.children[entry.key]
            self._journal_mutation("-", entry)
        del self._registered[bid]
        self.pool.release([bid])
        _M_EVICTIONS.inc()
        self.evictions += 1

    def _prune_parked(self, node: _Node) -> None:
        """Remove a parked node and its (all-parked) subtree from the
        trie and the tier store — the session re-prefills next turn."""
        parent = node.parent
        if parent is not None and parent.children.get(node.key) is node:
            del parent.children[node.key]
        stack = [node]
        while stack:
            cur = stack.pop()
            if self._tiers is not None:
                self._tiers.drop(cur)
            self._journal_mutation("-", cur)
            stack.extend(cur.children.values())
            cur.children.clear()

    def restore_path(self, tokens: "tuple[int, ...]",
                     alloc_block: "Callable[[], Optional[int]]",
                     install: "Callable[[int, Dict], bool]") -> "list[int]":
        """Page a parked prefix of ``tokens`` back onto the device
        ahead of :meth:`match` — the turn-resume path: one H2D copy
        per parked block instead of re-prefilling the whole prefix.

        Walks the block-aligned path; device nodes pass through
        untouched, parked nodes are fetched from their tier, given a
        fresh pool block from ``alloc_block()`` (which may demote
        *other* cold leaves — just-restored blocks hold a reference so
        they can't be victims), and written back by ``install(bid,
        payload)``. The walk stops at the first miss: allocation
        shortfall re-parks the payload (MRU — it is about to be wanted
        again); a corrupt payload or failed install (``kv.unpark``
        fault) prunes that node's parked subtree so the suffix simply
        re-prefills — the request always completes.

        Returns the restored block ids, each holding one reference the
        caller must :meth:`release` after ``match()`` takes its own."""
        if self._tiers is None:
            return []
        bs = self.block_size
        node = self._root
        restored: "list[int]" = []
        i = 0
        while len(tokens) - i >= bs:
            child = node.children.get(tokens[i:i + bs])
            if child is None:
                break
            if child.tier != "device":
                payload = self._tiers.fetch(child)
                if payload is None:
                    # spill lost or corrupt: drop the whole parked
                    # subtree (all parked below a parked node)
                    self._prune_parked(child)
                    break
                bid = alloc_block()
                if bid is None:
                    # pool shortfall: put it back at the MRU end and
                    # let the suffix re-prefill this turn
                    for lost in self._tiers.park(child, payload):
                        self._prune_parked(lost)
                    break
                if not install(bid, payload):
                    self.pool.release(self.pool.deref([bid]))
                    self._prune_parked(child)
                    break
                child.block_id = bid
                child.tier = "device"
                self._registered[bid] = child
                self.unparks += 1
                restored.append(bid)
            node = child
            i += bs
        return restored

    def parked_leaf_paths(self) -> "List[Tuple[tuple, List[_Node]]]":
        """``(tokens, root→leaf node path)`` for every parked leaf —
        one entry per resumable idle session, the export side of
        parked-session migration (ISSUE 19). The path may start with
        device-resident ancestors (a session that parked only its
        tail); the caller serializes those too so the importing host
        can adopt the WHOLE prefix. Call under the engine lock."""
        if self._tiers is None:
            return []
        out: "List[Tuple[tuple, List[_Node]]]" = []
        for leaf in list(self._tiers.nodes()):
            if leaf.children:
                continue
            path: "List[_Node]" = []
            cur = leaf
            while cur is not None and cur is not self._root:
                if (cur.parent is None
                        or cur.parent.children.get(cur.key) is not cur):
                    break  # orphaned by a racing prune: skip the leaf
                path.append(cur)
                cur = cur.parent
            if cur is not self._root:
                continue
            path.reverse()
            tokens = tuple(t for n in path for t in n.key)
            out.append((tokens, path))
        return out

    def adopt_parked(self, tokens: "tuple[int, ...]",
                     payloads: "List[Dict]") -> int:
        """Graft a migrated session's block-aligned prefix into this
        trie as PARKED nodes (ISSUE 19): ``payloads[i]`` holds the raw
        storage bytes for tokens ``[i*bs, (i+1)*bs)``. Spans this trie
        already holds (device or parked) keep their existing state —
        the resident bytes are identical by construction (KV is a pure
        function of the prefix). Returns blocks newly parked. The next
        turn's :meth:`restore_path` pages the path back in exactly as
        if it had parked here — one H2D per block, no re-prefill."""
        if self._tiers is None:
            raise RuntimeError(
                "adopt_parked needs a tier store (host_kv_blocks)")
        bs = self.block_size
        node = self._root
        adopted = 0
        for i, payload in enumerate(payloads):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            if len(key) < bs:
                break  # ragged tail: the digest grid is block-aligned
            child = node.children.get(key)
            if child is None:
                child = _Node(key, -1, node, next(self._clock))
                child.tier = "host"
                node.children[key] = child
                self._journal_mutation("+", child)
                self.parks += 1
                for lost in self._tiers.park(child, payload):
                    self._prune_parked(lost)
                if self._tiers.tier_of(child) is None:
                    # the park cascade dropped the adopted node itself
                    # (tiers full of protected entries): the rest of
                    # the path would dangle unreachable — stop here
                    break
                adopted += 1
            node = child
        return adopted

    def cold_blocks(self) -> int:
        """Refcount-0 registered device blocks — pressure that is
        *parkable*, not live (fabric placement wants the split)."""
        return sum(1 for bid in self._registered
                   if self.pool.refcount(bid) == 0)

    def parked_sessions(self) -> int:
        """Parked trie leaves — each is the tail of one idle session's
        prefix path, the engine's proxy for resumable conversations."""
        if self._tiers is None:
            return 0
        return sum(1 for node in self._tiers.nodes()
                   if not node.children)

    def tier_stats(self) -> "Optional[Dict[str, int]]":
        if self._tiers is None:
            return None
        s = self._tiers.stats()
        s["parked_sessions"] = self.parked_sessions()
        s["parks"] = self.parks
        s["unparks"] = self.unparks
        return s


def _common_prefix(a: tuple, b: tuple) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n
