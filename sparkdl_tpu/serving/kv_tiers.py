"""Host-DRAM + disk tiers under the paged KV pool (ROADMAP item 1).

Production chat traffic is mostly *idle* sessions: the user read the
reply and will come back in minutes. Keeping their KV blocks resident
burns device pool capacity; evicting them forces a full re-prefill on
the next turn. This module is the cheap middle ground — the same
host<->device overlap discipline the ingest stack proved out (tf.data:
transfers hide behind compute), applied to KV state:

* **host tier** — an LRU dict of raw per-block payloads fetched D2H via
  the AsyncFetcher path (:func:`~sparkdl_tpu.runtime.completion.
  start_fetch`). Host DRAM is ~10x the HBM of a chip, so parking a cold
  session here multiplies live sessions per chip by the same factor.
* **disk tier** — below the host tier, an LRU spill directory holding
  the same payloads through the :mod:`~sparkdl_tpu.disagg.handoff`
  raw-storage codec (base64 JSON, dtype-faithful). Bounded; overflow
  drops the coldest droppable entry entirely (that session re-prefills,
  which is exactly what would have happened without tiers).

Payloads are **storage-dtype raw** — for an int8 pool the parked bytes
are the int8 codes plus the per-column fp32 scales, never a dequantized
copy. That is both the 4x transfer saving the quantized layout already
bought and the reason a parked-then-resumed session is *bitwise*
identical to one that never parked: unpark writes back the exact bytes
the decode kernels would have read.

The store is deliberately dumb bookkeeping keyed by opaque handles (the
radix-trie nodes of :mod:`~sparkdl_tpu.serving.prefix_cache` own the
policy of *what* parks); it owns only LRU order, tier capacities, the
spill-file lifecycle, and the tier telemetry
(``sparkdl_kv_tier_blocks{tier}``, park/unpark counters). Like
``KVBlockPool`` it is not self-locking — callers serialize under the
engine lock.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import shutil
import tempfile
from typing import Callable, Dict, Hashable, List, Optional

from sparkdl_tpu.observability.registry import GaugeShare, registry


def _unlink_spill(path: str) -> None:
    """Remove a spill file and its tmp/sidecar companions (best
    effort): no publication artifact may outlive its disk-tier entry."""
    for p in (path, path + ".tmp", path + ".sha256"):
        try:
            os.unlink(p)
        except OSError:
            pass

_M_TIER = registry().gauge(
    "sparkdl_kv_tier_blocks",
    "KV blocks resident per cache tier (device = pool blocks_cached; "
    "host = parked in pinned DRAM; disk = spilled), all engines",
    labels=("tier",))
_M_PARKS = registry().counter(
    "sparkdl_kv_parks_total",
    "KV blocks demoted a tier (tier=host: device->host page-out; "
    "tier=disk: host->disk spill)", labels=("tier",))
_M_UNPARKS = registry().counter(
    "sparkdl_kv_unparks_total",
    "KV blocks paged back to the device on turn resume",
    labels=("tier",))
_M_FALLBACKS = registry().counter(
    "sparkdl_kv_park_fallbacks_total",
    "tiering operations abandoned for the plain path (op=park: torn "
    "page-out, blocks evicted instead; op=unpark: corrupt page-in, "
    "session re-prefills)", labels=("op",))
_M_MIGRATIONS = registry().counter(
    "sparkdl_kv_migrations_total",
    "parked sessions migrated between hosts on drain/scale-down "
    "(outcome=exported/imported: the two wire ends; export_failed/"
    "import_failed: torn migration, the session re-prefills instead)",
    labels=("outcome",))
_M_MIG_BLOCKS = registry().counter(
    "sparkdl_kv_migration_blocks_total",
    "KV blocks serialized onto the wire by parked-session migration")
_M_MIG_SEC = registry().histogram(
    "sparkdl_kv_migration_seconds",
    "wall seconds per parked-session migration call (one host's export "
    "or import batch)")
_M_PARK_SEC = registry().histogram(
    "sparkdl_kv_park_seconds",
    "wall seconds per park operation (D2H fetch + host insert, one "
    "session's cold blocks)")
_M_UNPARK_SEC = registry().histogram(
    "sparkdl_kv_unpark_seconds",
    "wall seconds per unpark operation (tier fetch + H2D install, one "
    "parked prefix path)")


def _set_tier(node: Hashable, tier: str) -> None:
    # Keep the owner's per-handle tier marker truthful across host->
    # disk demotion; tolerate handles without one (tests use tuples).
    try:
        node.tier = tier
    except (AttributeError, TypeError):
        pass


class TieredKVStore:
    """LRU host-DRAM tier with an LRU disk tier below it.

    ``park`` inserts at the MRU end of the host tier; host overflow
    demotes the LRU host entry to disk (when a disk tier is
    configured), disk overflow drops the LRU *droppable* entry (the
    ``is_droppable`` predicate lets the owner protect interior trie
    nodes whose children are still parked — dropping those would orphan
    reachable state). Dropped handles are returned so the owner can
    prune its index. ``fetch`` removes the entry from whichever tier
    holds it and returns the payload.

    Entries are one block each: a dict of numpy arrays in storage
    dtype (``k``/``v`` shaped ``[layers, block_size, H, D]`` plus
    ``k_scale``/``v_scale`` ``[layers, block_size]`` for quantized
    pools). The disk tier serializes through the handoff raw codec so
    bf16/int8 round-trip exactly.
    """

    def __init__(self, host_blocks: int, disk_blocks: int = 0,
                 spill_dir: Optional[str] = None,
                 is_droppable: Optional[Callable[[Hashable], bool]] = None):
        if host_blocks <= 0:
            raise ValueError("host_blocks must be positive")
        if disk_blocks < 0:
            raise ValueError("disk_blocks must be >= 0")
        self.host_blocks = int(host_blocks)
        self.disk_blocks = int(disk_blocks)
        self._is_droppable = is_droppable or (lambda node: True)
        self._host: "collections.OrderedDict[Hashable, Dict]" = (
            collections.OrderedDict())
        self._disk: "collections.OrderedDict[Hashable, str]" = (
            collections.OrderedDict())
        self._owns_dir = spill_dir is None and disk_blocks > 0
        self._dir = (tempfile.mkdtemp(prefix="sparkdl-kv-spill-")
                     if self._owns_dir else spill_dir)
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)
        self._seq = 0
        self._g_host = GaugeShare(_M_TIER.labels(tier="host"))
        self._g_disk = GaugeShare(_M_TIER.labels(tier="disk"))
        self._closed = False

    # -- occupancy -----------------------------------------------------------
    @property
    def host_used(self) -> int:
        return len(self._host)

    @property
    def disk_used(self) -> int:
        return len(self._disk)

    def __contains__(self, node: Hashable) -> bool:
        return node in self._host or node in self._disk

    def nodes(self):
        """All parked handles, host tier first (LRU -> MRU each)."""
        yield from self._host
        yield from self._disk

    def tier_of(self, node: Hashable) -> Optional[str]:
        if node in self._host:
            return "host"
        if node in self._disk:
            return "disk"
        return None

    def stats(self) -> Dict[str, int]:
        return {
            "host_blocks": len(self._host),
            "host_capacity": self.host_blocks,
            "disk_blocks": len(self._disk),
            "disk_capacity": self.disk_blocks,
        }

    # -- tier movement -------------------------------------------------------
    def park(self, node: Hashable, payload: Dict) -> List[Hashable]:
        """Insert one block at the host tier's MRU end.

        Returns the handles *dropped entirely* by the resulting
        cascade (host->disk demotions stay resident and are not
        reported). The caller prunes its index for each dropped
        handle — those sessions re-prefill on their next turn.
        """
        dropped: List[Hashable] = []
        self._host[node] = payload
        self._host.move_to_end(node)
        _set_tier(node, "host")
        _M_PARKS.inc(tier="host")
        while len(self._host) > self.host_blocks:
            lru, lru_payload = next(iter(self._host.items()))
            del self._host[lru]
            if self.disk_blocks > 0 and self._spill(lru, lru_payload):
                _set_tier(lru, "disk")
                _M_PARKS.inc(tier="disk")
                dropped.extend(self._trim_disk())
            else:
                dropped.append(lru)
        self._update_gauges()
        return dropped

    def fetch(self, node: Hashable) -> Optional[Dict]:
        """Remove ``node`` from its tier and return its payload.

        Returns ``None`` when the node is not resident (already
        dropped) or its spill file fails to load (corrupt unpark — the
        caller falls back to re-prefill either way).
        """
        payload = self._host.pop(node, None)
        if payload is not None:
            _M_UNPARKS.inc(tier="host")
            self._update_gauges()
            return payload
        path = self._disk.pop(node, None)
        if path is not None:
            self._update_gauges()
            try:
                payload = self._load(path)
            except Exception:
                payload = None  # torn/corrupt spill: prune, re-prefill
            finally:
                _unlink_spill(path)
            if payload is not None:
                _M_UNPARKS.inc(tier="disk")
            return payload
        return None

    def peek(self, node: Hashable) -> Optional[Dict]:
        """Read ``node``'s payload WITHOUT removing it from its tier —
        the migration-export read (ISSUE 19): the bytes go onto the
        wire while the local entry stays authoritative until the
        importing host confirms. No LRU touch, no unpark accounting
        (the block is not coming back to the device here). ``None``
        when not resident or the spill file fails to load."""
        payload = self._host.get(node)
        if payload is not None:
            return payload
        path = self._disk.get(node)
        if path is not None:
            try:
                return self._load(path)
            except Exception:
                return None
        return None

    def drop(self, node: Hashable) -> None:
        """Discard ``node`` from whichever tier holds it (no fetch)."""
        if self._host.pop(node, None) is None:
            path = self._disk.pop(node, None)
            if path is not None:
                _unlink_spill(path)
        self._update_gauges()

    def _trim_disk(self) -> List[Hashable]:
        dropped: List[Hashable] = []
        while len(self._disk) > self.disk_blocks:
            victim = next(
                (n for n in self._disk if self._is_droppable(n)), None)
            if victim is None:
                break  # only protected interior entries: soft-exceed
            self.drop(victim)
            dropped.append(victim)
        return dropped

    # -- disk codec ----------------------------------------------------------
    def _spill(self, node: Hashable, payload: Dict) -> bool:
        if not self._dir:
            return False
        # Reuse the handoff raw-storage codec: dtype-faithful (bf16 and
        # int8 round-trip exactly), self-describing, no extra deps.
        from sparkdl_tpu.disagg.handoff import _enc

        self._seq += 1
        path = os.path.join(self._dir, f"kvblk-{self._seq:08d}.json")
        # Crash-safe publication (ISSUE 20, the checkpoint-integrity
        # scheme): serialize once, write to a tmp file, fsync, then
        # os.replace into the final name with a sha256 sidecar — a
        # writer killed mid-spill leaves a *.tmp (never adopted) or a
        # digest mismatch, and _load turns either into the existing
        # corrupt-unpark fallback (prune + re-prefill) instead of a
        # json-decode crash on a torn file.
        blob = json.dumps({k: _enc(v) for k, v in payload.items()})
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            with open(path + ".sha256", "w") as f:
                f.write(hashlib.sha256(blob.encode("utf-8")).hexdigest())
            os.replace(tmp, path)
        except OSError:
            _unlink_spill(path)
            return False
        self._disk[node] = path
        self._disk.move_to_end(node)
        return True

    def _load(self, path: str) -> Dict:
        from sparkdl_tpu.disagg.handoff import _dec

        with open(path + ".sha256") as f:
            want = f.read().strip()
        with open(path, "rb") as f:
            raw = f.read()
        got = hashlib.sha256(raw).hexdigest()
        if got != want:
            raise ValueError(
                f"torn spill file {path}: sha256 {got[:12]} != "
                f"sidecar {want[:12]}")
        blob = json.loads(raw.decode("utf-8"))
        return {k: _dec(v) for k, v in blob.items()}

    def _update_gauges(self) -> None:
        if self._closed:
            return
        self._g_host.set(len(self._host))
        self._g_disk.set(len(self._disk))

    def close(self) -> None:
        """Retract gauge contributions and remove owned spill files."""
        if self._closed:
            return
        self._g_host.set(0)
        self._g_disk.set(0)
        self._closed = True
        self._host.clear()
        if self._owns_dir and self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
        else:
            for path in self._disk.values():
                _unlink_spill(path)
        self._disk.clear()
