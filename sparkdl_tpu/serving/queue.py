"""Bounded async request queue: admission control, deadlines, backpressure.

The front door of the online serving engine (ROADMAP: "serves heavy
traffic"): callers submit individual requests and get a
``concurrent.futures.Future`` back immediately; the dispatch loop drains
the queue into device batches. Admission is bounded — past ``max_depth``
the submit *raises* (:class:`QueueFullError`) instead of buffering
unboundedly, the reject-with-error backpressure that keeps tail latency
honest under overload (the tf.data lesson: queue growth only moves the
stall, it never removes it). Every request may carry a deadline; expired
requests fail with :class:`DeadlineExceededError` at the next sweep
instead of wasting a batch slot.

Multi-tenant QoS (ISSUE 20): every request carries a ``tenant`` and an
integer ``priority`` class (lower = more urgent; the defaults reproduce
the old single-FIFO behavior bitwise). Internally the queue is a set of
per-(priority, tenant) sub-queues: :meth:`take` serves classes in
strict priority order and tenants *within* a class by deficit-weighted
round-robin (weights from an attached
:class:`~sparkdl_tpu.serving.tenancy.TenantRegistry`), so one tenant's
deep backlog cannot monopolize micro-batch slots. The registry — when
attached — also gates admission: an over-quota submit raises
:class:`~sparkdl_tpu.serving.tenancy.TenantThrottledError` at the door,
before consuming queue depth, and the process-wide brownout ladder
(:class:`~sparkdl_tpu.serving.tenancy.OverloadController`) may shed the
background class or everything. :meth:`requeue` returns a request to
the head of ITS OWN class — a deferred or preempted background victim
re-enters ahead of its class-mates but never jumps an interactive
tenant.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Iterator

from sparkdl_tpu.observability import flight, tracing
from sparkdl_tpu.observability.registry import GaugeShare, registry
from sparkdl_tpu.serving import tenancy

# Registry mirrors of the queue's own counters (ISSUE 2: the spine sees
# admission control without asking each engine for its snapshot). Family
# handles are import-time singletons; registry().reset() zeroes values
# but keeps declarations, so these never go stale.
_M_SUBMITTED = registry().counter(
    "sparkdl_queue_submitted_total", "requests admitted to a RequestQueue")
_M_REJECTED = registry().counter(
    "sparkdl_queue_rejected_total", "admission rejects (queue at max depth)")
_M_EXPIRED = registry().counter(
    "sparkdl_queue_expired_total", "requests whose deadline passed in queue")
_M_CANCELLED = registry().counter(
    "sparkdl_queue_cancelled_total", "requests cancelled by their caller")
_M_DEPTH = registry().gauge(
    "sparkdl_queue_depth", "currently queued requests, all queues")
_M_WAIT = registry().histogram(
    "sparkdl_queue_wait_seconds", "queue wait, submit to take")
_M_REQUEUED = registry().counter(
    "sparkdl_queue_requeued_total",
    "taken requests returned to the queue head (deferred admission, "
    "e.g. KV block-pool exhaustion)")
_M_FAILED = registry().counter(
    "sparkdl_requests_failed_total",
    "accepted requests that resolved with an error, by reason "
    "(closed/expired/replica_lost/retry_exhausted/error)",
    labels=("reason",))


class QueueFullError(RuntimeError):
    """Admission reject: queue at max depth (backpressure — retry later)."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before a result was produced."""


class EngineClosedError(RuntimeError):
    """Submit after close(): the engine is draining or stopped."""


def failure_reason(exc: BaseException) -> str:
    """Classify a request-failing exception for the shed-load counter.

    Name-based matches keep this module import-light: the replica-pool
    and retry errors live in modules this one must not depend on.
    """
    if isinstance(exc, EngineClosedError):
        return "closed"
    if isinstance(exc, DeadlineExceededError):
        return "expired"
    name = type(exc).__name__
    if name in ("AllReplicasQuarantinedError", "HungDispatchError"):
        return "replica_lost"
    if name == "RetryExhaustedError":
        return "retry_exhausted"
    return "error"


def record_request_failure(exc: BaseException,
                           request_id: "int | None" = None) -> None:
    """Land one failed-request outcome in the registry
    (``sparkdl_requests_failed_total{reason=...}``) and the flight
    recorder so shed load is observable — called by every path that
    fails an accepted request's Future (queue sweeps, drains, and the
    micro-batcher)."""
    reason = failure_reason(exc)
    _M_FAILED.inc(reason=reason)
    flight.record_event(
        "request.failed", reason=reason, error=type(exc).__name__,
        request_id=request_id,
    )


@dataclasses.dataclass
class Request:
    """One queued unit of work. ``deadline`` is absolute ``time.monotonic``
    seconds (None = no deadline); ``enqueued`` stamps queue-wait metrics.
    ``request_id`` is the process-unique id submit allocated (also the
    caller-visible ``future.request_id`` and, with tracing on, the
    request's trace id); ``trace_ctx`` is the root span context of that
    trace (None with tracing off — the id is the only per-request cost),
    carried across thread boundaries so every stage span of this request
    lands in its trace."""

    payload: Any
    future: Future
    deadline: float | None
    enqueued: float
    trace_ctx: "tracing.SpanContext | None" = None
    request_id: int = 0
    #: the submitter's ambient span at submit time (None with tracing
    #: off or a span-less caller): its trace id rides the queue-wait
    #: span's links, joining the caller's trace to the request's
    submitter_ctx: "tracing.SpanContext | None" = None
    #: True once take() moved the Future to RUNNING. A deferred request
    #: (requeue()) comes back with ``started`` set, so the next take
    #: skips the set_running handshake (a Future runs only once) and
    #: the caller can no longer cancel it — it was already admitted.
    started: bool = False
    #: monotonic stamp of the FIRST take (set alongside ``started``):
    #: the queue-wait/compute phase boundary the per-request latency
    #: attribution differences against (ISSUE 17) — a deferred retake
    #: keeps the original stamp, matching the wait histogram's
    #: first-take-only policy.
    taken_at: "float | None" = None
    #: tenant identity (ISSUE 20): scopes quota, fair-share weight, and
    #: per-tenant accounting. The default tenant — unconfigured — is
    #: the bitwise-compatible single-user path.
    tenant: str = "default"
    #: priority class (lower = more urgent): classes are served in
    #: strict order, and requeue/extract preserve class membership so
    #: a background victim can never jump an interactive tenant.
    priority: int = 0

    def expired(self, now: float | None = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    def fail_expired(self) -> None:
        exc = DeadlineExceededError(
            f"deadline exceeded after "
            f"{time.monotonic() - self.enqueued:.3f}s in queue"
        )
        if self.started:
            # already RUNNING (a deferred admission): fail directly
            record_request_failure(exc, request_id=self.request_id)
            self.future.set_exception(exc)
        elif self.future.set_running_or_notify_cancel():
            # a future the caller already cancelled cannot take an
            # exception — the handshake filters those
            record_request_failure(exc, request_id=self.request_id)
            self.future.set_exception(exc)


class _OneClass:
    """One priority class: per-tenant FIFO deques + DRR rotation state
    (mutated only under the owning queue's condition lock)."""

    __slots__ = ("queues", "order", "ptr", "credit")

    def __init__(self):
        self.queues: "dict[str, collections.deque[Request]]" = {}
        self.order: "list[str]" = []  # rotation order (arrival order)
        self.ptr = 0
        self.credit: "dict[str, float]" = {}


class _FairQueue:
    """Strict-priority classes, deficit-weighted round-robin tenants.

    The drop-in replacement for the queue's old single deque: with one
    tenant in one class (the default path) every operation degenerates
    to the exact FIFO it replaced. ``weight_of`` maps a tenant to its
    DRR share (>= 1; a weight-2 tenant drains two requests per
    rotation visit for a weight-1 tenant's one). Unit-cost DRR: each
    visit tops the tenant's credit up by its weight and serves while
    credit lasts, so fractional weights never stall the rotation.
    Iteration (and :meth:`drain`) walks classes in priority order and
    tenants in rotation order — the class-preserving transfer order
    ``extract_pending`` hands to a surviving host.
    """

    __slots__ = ("_classes", "_weight_of", "_n")

    def __init__(self, weight_of: "Callable[[str], float]"):
        self._classes: "dict[int, _OneClass]" = {}
        self._weight_of = weight_of
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def _class(self, priority: int) -> _OneClass:
        cls = self._classes.get(priority)
        if cls is None:
            cls = self._classes[priority] = _OneClass()
        return cls

    def _enqueue(self, req: Request, *, left: bool) -> None:
        cls = self._class(req.priority)
        q = cls.queues.get(req.tenant)
        if q is None:
            q = cls.queues[req.tenant] = collections.deque()
            cls.order.append(req.tenant)
        (q.appendleft if left else q.append)(req)
        self._n += 1

    def append(self, req: Request) -> None:
        self._enqueue(req, left=False)

    def appendleft(self, req: Request) -> None:
        """Head of the request's OWN class — a requeued victim re-enters
        ahead of its class-mates, never ahead of a more urgent class."""
        self._enqueue(req, left=True)

    def popnext(self) -> "Request | None":
        """Next request: most urgent non-empty class, DRR tenant pick."""
        for priority in sorted(self._classes):
            cls = self._classes[priority]
            req = self._pop_class(cls)
            if req is not None:
                if not cls.queues:
                    del self._classes[priority]
                self._n -= 1
                return req
            del self._classes[priority]
        return None

    def _pop_class(self, cls: _OneClass) -> "Request | None":
        while cls.order:
            idx = cls.ptr % len(cls.order)
            tenant = cls.order[idx]
            q = cls.queues.get(tenant)
            if not q:
                # drained tenant leaves the rotation; credit resets —
                # an idle tenant must not bank a burst of turns
                cls.order.pop(idx)
                cls.queues.pop(tenant, None)
                cls.credit.pop(tenant, None)
                continue
            credit = cls.credit.get(tenant, 0.0)
            if credit < 1.0:
                credit += max(1.0, self._weight_of(tenant))
            credit -= 1.0
            req = q.popleft()
            if credit < 1.0:
                cls.ptr = idx + 1
            cls.credit[tenant] = credit
            return req
        return None

    def highest_priority(self) -> "int | None":
        """Most urgent class with queued work (None when empty) — the
        engine's preemption test reads this without popping."""
        live = [p for p, cls in self._classes.items()
                if any(cls.queues.values())]
        return min(live) if live else None

    def __iter__(self) -> "Iterator[Request]":
        for priority in sorted(self._classes):
            cls = self._classes[priority]
            order = [t for t in cls.order if cls.queues.get(t)]
            if order:
                pivot = cls.ptr % len(order)
                order = order[pivot:] + order[:pivot]
            for tenant in order:
                yield from cls.queues.get(tenant, ())

    def drain(self) -> "list[Request]":
        """Remove and return everything, class order preserved."""
        out = list(self)
        self.clear()
        return out

    def clear(self) -> None:
        self._classes.clear()
        self._n = 0

    def sweep(self, keep: "Callable[[Request], bool]") -> "list[Request]":
        """Drop (and return) every request failing ``keep``, in place —
        per-tenant FIFO order and DRR state untouched for survivors."""
        removed: "list[Request]" = []
        for priority in list(self._classes):
            cls = self._classes[priority]
            for tenant, q in list(cls.queues.items()):
                live = [r for r in q if keep(r)]
                if len(live) != len(q):
                    removed.extend(r for r in q if not keep(r))
                    q.clear()
                    q.extend(live)
        self._n -= len(removed)
        return removed


class RequestQueue:
    """Thread-safe bounded multi-class queue of :class:`Request`.

    ``submit`` is the producer side (any number of caller threads);
    ``take`` is the consumer side (the dispatch loop). Expired requests
    are swept — failed with DeadlineExceededError, never handed to the
    batcher — on every take, and on submit when at capacity (so a full
    queue of dead requests does not reject live traffic).

    ``tenants`` (a :class:`~sparkdl_tpu.serving.tenancy.TenantRegistry`,
    settable any time) turns on per-tenant admission quotas and DRR
    weights; without it every tenant passes freely at weight 1 and the
    single default class is an exact FIFO — the pre-tenancy behavior.
    """

    def __init__(self, max_depth: int = 256,
                 tenants: "tenancy.TenantRegistry | None" = None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        #: per-tenant quota/weight policy (None = no tenancy: the
        #: bitwise-compatible default). Plain attribute: operators may
        #: attach/replace a registry on a live queue.
        self.tenants = tenants
        self._dq = _FairQueue(self._tenant_weight)
        self._cv = threading.Condition()
        self._closed = False
        #: the gauge carries the SUM over all live queues: each queue
        #: contributes deltas of its own depth (registry.GaugeShare —
        #: the same reset-safe pattern the KV block pool uses)
        self._depth_share = GaugeShare(_M_DEPTH)
        #: monotonically increasing counters (read under no lock: ints)
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.cancelled = 0
        self.requeued = 0

    def _tenant_weight(self, tenant: str) -> float:
        reg = self.tenants
        return reg.weight(tenant) if reg is not None else 1.0

    def _update_depth_locked(self) -> None:
        """Push this queue's depth change to the shared gauge as a delta
        (called under ``self._cv``)."""
        self._depth_share.set(len(self._dq))

    @property
    def depth(self) -> int:
        return len(self._dq)

    def highest_waiting_priority(self) -> "int | None":
        """Most urgent class with queued work (None when empty) — the
        engine's preemption test: when this is strictly more urgent
        than an in-flight background prefill and no slot is free, the
        engine may preempt (ISSUE 20)."""
        with self._cv:
            return self._dq.highest_priority()

    def pending_request_ids(self) -> "list[int]":
        """Request ids currently queued (flight-recorder postmortems
        resolve these to in-flight traces)."""
        with self._cv:
            return [r.request_id for r in self._dq]

    @property
    def closed(self) -> bool:
        return self._closed

    def submit(self, payload: Any, *,
               timeout_s: float | None = None,
               tenant: str = "default",
               priority: "int | None" = None) -> Future:
        """Enqueue; returns the request's Future. Raises
        :class:`QueueFullError` at capacity (after sweeping expired
        entries) and :class:`EngineClosedError` after close().

        ``tenant``/``priority`` scope the request for quota and
        scheduling (ISSUE 20): with a :attr:`tenants` registry attached
        an over-quota submit raises
        :class:`~sparkdl_tpu.serving.tenancy.TenantThrottledError`
        BEFORE consuming queue depth, and the process-wide brownout
        ladder may shed it
        (:class:`~sparkdl_tpu.serving.tenancy.BrownoutShedError`) —
        both typed admission rejects, never timeouts. ``priority=None``
        resolves to the tenant's configured default class, else the
        interactive class 0. Quota sheds do NOT count into
        ``sparkdl_queue_rejected_total`` — a flooder's shed overage
        must not burn the fleet availability SLO the compliant tenants
        are measured by (it lands in ``sparkdl_tenant_shed_total``).

        Submit vs a concurrent ``close()`` is deterministic: both take
        the queue's condition lock, so a submit either wins the race (its
        request was accepted and WILL be drained — ``close()`` keeps
        queued work takeable) or raises ``EngineClosedError`` — never a
        silently dropped Future (pinned by tests).

        The returned Future carries ``request_id`` — the process-unique
        id that doubles as the request's trace id
        (``ServingEngine.trace(fut.request_id)`` replays its spans when
        tracing is on)."""
        now = time.monotonic()
        deadline = now + timeout_s if timeout_s is not None else None
        reg = self.tenants
        prio = priority
        if prio is None and reg is not None:
            prio = reg.default_priority(tenant)
        if prio is None:
            prio = tenancy.PRIORITY_INTERACTIVE
        # tenancy gates run BEFORE the queue lock (they take the
        # registry's own lock) and before depth is consumed: shed
        # traffic never holds a slot it is not getting
        ctrl = tenancy.process_overload()
        if ctrl is not None:
            try:
                ctrl.admission_check(tenant, prio)
            except tenancy.BrownoutShedError:
                if reg is not None:
                    reg.count_shed(tenant)
                raise
        if reg is not None:
            reg.admit(tenant, now,
                      cost=ctrl.admit_cost() if ctrl is not None else 1.0)
        rid = tracing.next_request_id()
        with self._cv:
            if self._closed:
                raise EngineClosedError("queue is closed to new requests")
            if len(self._dq) >= self.max_depth:
                self._sweep_expired_locked(now)
            if len(self._dq) >= self.max_depth:
                self.rejected += 1
                _M_REJECTED.inc()
                raise QueueFullError(
                    f"queue at max depth {self.max_depth}; retry with "
                    "backoff or raise capacity"
                )
            fut: Future = Future()
            fut.request_id = rid
            self._dq.append(Request(
                payload, fut, deadline, now,
                trace_ctx=tracing.request_context(rid),
                request_id=rid,
                submitter_ctx=tracing.current_context(),
                tenant=tenant, priority=prio,
            ))
            self.submitted += 1
            _M_SUBMITTED.inc()
            self._update_depth_locked()
            self._cv.notify()
            return fut

    def take(self, max_n: int, max_wait_s: float) -> list[Request]:
        """Dispatch-side drain: block up to ``max_wait_s`` for the first
        live request, then return every immediately-available live request
        up to ``max_n`` (the micro-batching max-wait/max-batch policy —
        the first arrival pays at most ``max_wait_s`` extra latency,
        followers ride along for free). Returns [] on timeout or close.

        Requests whose Future was cancelled by the caller are dropped;
        expired requests are failed and skipped.
        """
        if max_n < 1:
            return []
        end = time.monotonic() + max_wait_s
        out: list[Request] = []
        fresh: list[Request] = []
        with self._cv:
            while not self._dq and not self._closed:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return []
                self._cv.wait(remaining)
            now = time.monotonic()
            while self._dq and len(out) < max_n:
                req = self._dq.popnext()
                if req is None:
                    break
                if req.expired(now):
                    self.expired += 1
                    _M_EXPIRED.inc()
                    req.fail_expired()
                    continue
                # a caller that cancelled its Future no longer wants the
                # result; set_running_or_notify_cancel is the handshake
                # (skipped for requeued requests — already RUNNING)
                if not req.started:
                    if not req.future.set_running_or_notify_cancel():
                        self.cancelled += 1
                        _M_CANCELLED.inc()
                        continue
                    req.started = True
                    req.taken_at = now
                    fresh.append(req)
                out.append(req)
            self._update_depth_locked()
        # wait metrics/spans on the FIRST take only: a deferred request
        # is retaken once per engine tick, and re-observing its
        # cumulative wait each time would inflate the histogram and
        # flood the span ring exactly during the exhaustion incident
        for req in fresh:
            _M_WAIT.observe(now - req.enqueued)
            # retroactive span: the wait started at submit, long before
            # this instrumentation point, parented on the request's
            # root; the submitter's trace rides the links so a caller's
            # own span ("client_call") still reaches the request trace
            # via spans_for_trace(caller_trace_id)
            sub = req.submitter_ctx
            tracing.record_span(
                "serving.queue_wait", req.enqueued, now,
                parent=req.trace_ctx, request_id=req.request_id,
                **({"links": [sub.trace_id]} if sub is not None else {}),
            )
        return out

    def close(self) -> None:
        """Stop admission (submit raises EngineClosedError); queued
        requests stay takeable so the engine can drain gracefully."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def fail_pending(self, exc: BaseException | None = None) -> int:
        """Fail every queued request (non-graceful shutdown). Returns the
        number failed. Each failure lands in
        ``sparkdl_requests_failed_total`` under the exception's reason
        (``closed`` for the default shutdown error)."""
        if exc is None:
            exc = EngineClosedError("engine shut down before dispatch")
        n = 0
        with self._cv:
            for req in self._dq.drain():
                if req.started or req.future.set_running_or_notify_cancel():
                    record_request_failure(exc, request_id=req.request_id)
                    req.future.set_exception(exc)
                else:
                    self.cancelled += 1
                    _M_CANCELLED.inc()
                n += 1
            self._update_depth_locked()
        return n

    def requeue(self, requests: "list[Request]") -> None:
        """Return taken requests to the head of their OWN CLASS, in
        order — deferred admission (the engine took them but cannot
        place them yet, e.g. the KV block pool is exhausted) and
        priority preemption (the victim re-enters ahead of its
        class-mates). Head-of-class, not head-of-global-FIFO
        (ISSUE 20): a requeued background victim is retaken before
        everything ITS class submitted after it, but an interactive
        tenant's queued work still goes first — failover/preemption
        cannot let background work jump the interactive classes. With
        one tenant in one class (the default path) this is exactly the
        old head-of-queue semantics. Works on a closed queue: the
        requests were admitted before close() and close keeps queued
        work takeable.

        The requests need not have come from THIS queue: a drained or
        failed host's unstarted requests (``extract_pending`` on the
        dying queue) are handed to a surviving queue through this same
        call — the :class:`Request` carries its trace id, absolute
        deadline, and ``started`` flag, so nothing about the request's
        identity or accounting resets on transfer. The transfer itself
        is NOT a failure: no Future is touched and nothing lands in
        ``sparkdl_requests_failed_total`` — if the re-routed request
        later fails it is counted once, by its new owner (and if it
        succeeds, it was never counted at all). A transfer may
        transiently push this queue past ``max_depth`` (bounded by the
        dying queue's depth); admission control applies to NEW submits
        only — already-accepted traffic is never re-rejected."""
        if not requests:
            return
        with self._cv:
            for req in reversed(requests):
                self._dq.appendleft(req)
            self.requeued += len(requests)
            _M_REQUEUED.inc(len(requests))
            self._update_depth_locked()
            self._cv.notify_all()

    def adopt(self, req: Request) -> None:
        """Enqueue an ALREADY-ACCEPTED request at the tail — the
        cross-tier admission primitive (ISSUE 16): a decode tier adopts
        a request whose prefill finished on another tier. Unlike
        :meth:`submit` there is no depth check and no new Future — the
        request was admitted (and counted) once, at the prefill tier's
        front door, and re-rejecting accepted traffic would break the
        zero-loss contract exactly like re-rejecting a transfer would
        (see :meth:`requeue`). Unlike :meth:`requeue` the request joins
        at the TAIL: it is new work for THIS tier, not deferred work
        this tier owes. Raises :class:`EngineClosedError` on a closed
        queue — a HOST-level error, so a router fails over to another
        decode host instead of losing the handoff."""
        with self._cv:
            if self._closed:
                raise EngineClosedError(
                    "queue is closed to new requests")
            self._dq.append(req)
            self.submitted += 1
            _M_SUBMITTED.inc()
            self._update_depth_locked()
            self._cv.notify()

    def reopen(self) -> None:
        """Reverse :meth:`close`: accept new submits again — the
        spare-host rejoin path (ISSUE 16): a handle drained and parked
        by the autoscaler re-enters service via ``Router.add_host``.
        Only meaningful while the owning engine's loop is still (or
        again) running; queued state is untouched."""
        with self._cv:
            self._closed = False
            self._cv.notify_all()

    def extract_pending(self) -> "list[Request]":
        """Remove and return every queued request WITHOUT resolving its
        Future — the drain/transfer primitive (ISSUE 14): a draining or
        dying host extracts its not-yet-placed requests here and hands
        them to a surviving host's queue via :meth:`requeue`. Futures,
        trace ids, deadlines, and ``started`` flags ride along
        untouched, and nothing is recorded as failed — the requests are
        moving, not dying. Deferred requests (``started=True``, taken
        once then re-queued on pool exhaustion) are included: they hold
        no device state, so they transfer as cleanly as fresh ones.
        Call after :meth:`close` so no new submit races the drain.

        Order is class-preserving (ISSUE 20): requests come out most
        urgent class first, tenants within a class in their rotation
        order — so a surviving host's :meth:`requeue` (which re-inserts
        head-of-own-class) reproduces the same relative schedule the
        dying host owed, and a background victim cannot jump an
        interactive tenant through failover."""
        with self._cv:
            out = self._dq.drain()
            self._update_depth_locked()
        return out

    def sweep_expired(self) -> None:
        """Fail every expired queued request now. take() sweeps anyway;
        engines call this when they are NOT taking (all slots busy) so a
        dead request's caller hears promptly instead of at the next free
        slot."""
        with self._cv:
            self._sweep_expired_locked(time.monotonic())

    def _sweep_expired_locked(self, now: float) -> None:
        for r in self._dq.sweep(lambda r: not r.expired(now)):
            self.expired += 1
            _M_EXPIRED.inc()
            r.fail_expired()
        self._update_depth_locked()
