"""Dynamic micro-batching dispatch loop over a BatchedRunner/ReplicaPool.

The chip-saturation half of the serving engine: individual requests (one
row each) coalesce into the bucketed, jit-cached device batches the batch
pipeline already compiles (``transformers/_inference.BatchedRunner`` —
including its automatic dp sharding on multi-chip hosts, or a
``serving/replicas.ReplicaPool`` routing whole micro-batches over one
pinned executor per chip). Policy is the classic max-wait/max-batch: the
first request in an empty queue waits at most ``max_wait_s`` before
dispatch; every request that arrives in that window rides the same
device program for free.

Completion is pipelined (ISSUE 4): when the runner exposes
``run_batch_async`` (both BatchedRunner and ReplicaPool do), the loop
dispatches micro-batch i+1 while micro-batch i's device→host readback is
still in flight, resolving up to ``max_inflight_batches`` outstanding
dispatches in submission order — assembly and readback hide behind
compute instead of serializing with it, and on a replica pool the
in-flight window is what keeps N chips busy at once.

Robustness contract: a bad request degrades to ITS error, never the
batch's. Extraction failures (shared :func:`try_extract` convention) fail
per request before stacking; a dispatch failure of a multi-row batch
falls back to per-row dispatch so healthy neighbors of a poison row still
get results.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable

import numpy as np

from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.serving.metrics import ServingMetrics
from sparkdl_tpu.serving.queue import (
    Request,
    RequestQueue,
    record_request_failure,
)
from sparkdl_tpu.transformers._inference import BatchedRunner, try_extract

_log = logging.getLogger(__name__)


class _Resolved:
    """Future surface over an already-computed sync ``run_batch`` result
    (the fallback for runner objects without ``run_batch_async``)."""

    __slots__ = ("_value",)

    def __init__(self, value: Any):
        self._value = value

    def result(self, timeout: "float | None" = None) -> Any:
        return self._value


class MicroBatcher:
    """Drains a :class:`RequestQueue` into ``runner.run_batch*`` dispatches.

    ``extract`` (optional) maps a request payload to the feature dict the
    runner eats — same role as the partition path's extract, same
    per-row-error semantics. Without it, payloads must already be feature
    dicts of per-row arrays (no batch dim; the batcher stacks).

    ``max_inflight`` bounds how many dispatched-but-unresolved
    micro-batches the loop keeps in flight (None = the runner's
    ``max_inflight_batches``: 2 for a single async runner, healthy
    replicas + 1 for a pool). 1 restores the strictly serial
    dispatch-then-resolve loop.
    """

    def __init__(self, queue: RequestQueue, runner: BatchedRunner, *,
                 max_wait_s: float = 0.005,
                 extract: Callable[[Any], dict[str, np.ndarray]] | None = None,
                 metrics: ServingMetrics | None = None,
                 max_inflight: "int | None" = None):
        if max_inflight is not None and max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}"
            )
        self.queue = queue
        self.runner = runner
        self.max_wait_s = max_wait_s
        self.extract = extract
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.max_inflight = (
            max_inflight if max_inflight is not None
            else max(1, getattr(runner, "max_inflight_batches", 1))
        )
        #: dispatched, unresolved batches: (live requests, feeds, future,
        #: trace ctx) in submission order
        self._pending: "collections.deque[tuple]" = collections.deque()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(
            target=self._loop, name="sparkdl-microbatcher", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, *, drain: bool = True,
                 timeout_s: float | None = 30.0) -> None:
        """Stop the loop. ``drain=True`` (graceful): close admission,
        serve everything already queued, then stop. ``drain=False``: fail
        queued requests with EngineClosedError and stop now."""
        self.queue.close()
        if not drain:
            self.queue.fail_pending()
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            if self._thread.is_alive():  # pragma: no cover - watchdog only
                _log.warning("micro-batcher did not stop in %ss", timeout_s)
        elif drain:  # never started: drain inline so no future is stranded
            while True:
                reqs = self.queue.take(self.runner.chunk_size, 0.0)
                if not reqs:
                    break
                self._dispatch(reqs)
            self._resolve_pending(0)
        self._stop.set()
        # a timed-out join or crashed loop may leave queued requests
        # behind: no Future may ever be left unresolved
        self._fail_inflight()
        self.queue.fail_pending()

    # -- dispatch ------------------------------------------------------------
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self._pending:
                    # Batches in flight: dispatch ahead ONLY when a full
                    # bucket is already queued (or we are draining) —
                    # otherwise collect the oldest readback first, so the
                    # coalescing window keeps filling underneath exactly
                    # as it did when the dispatch itself blocked. Without
                    # this, pipelining would eagerly grab 2-row batches
                    # and trade occupancy for depth.
                    if (self.queue.closed
                            or self.queue.depth >= self.runner.chunk_size):
                        reqs = self.queue.take(self.runner.chunk_size, 0.0)
                        if reqs:
                            self._dispatch(reqs)
                            continue
                    self._resolve_pending(len(self._pending) - 1)
                    continue
                reqs = self.queue.take(self.runner.chunk_size,
                                       self.max_wait_s)
                if not reqs:
                    if self.queue.closed and self.queue.depth == 0:
                        break  # graceful drain complete
                    continue
                self._dispatch(reqs)
        except BaseException as e:
            # _dispatch contains per-batch error handling; anything that
            # escapes is fatal — fail the queue rather than strand callers
            exc = (e if isinstance(e, Exception)
                   else RuntimeError(f"micro-batcher loop died: {e!r}"))
            self.queue.close()
            self._fail_inflight(exc)
            self.queue.fail_pending(exc)
            raise
        else:
            self._resolve_pending(0)

    def _dispatch(self, reqs: list[Request]) -> None:
        # Batch-level work (one device dispatch, many riders) runs in
        # its OWN trace; the riders' request ids ride every batch span
        # as a `links` list, fanning the batch into each rider's trace
        # (ISSUE 9: spans_for_trace follows the links both ways).
        batch_ctx = tracing.new_trace_context()  # None with tracing off
        with tracing.attach(batch_ctx):
            self._dispatch_traced(reqs, batch_ctx)

    def _dispatch_traced(self, reqs: list[Request],
                         batch_ctx) -> None:
        links = ([r.request_id for r in reqs]
                 if batch_ctx is not None else ())
        feeds: list[dict[str, np.ndarray]] = []
        live: list[Request] = []
        with span("serving.batch_assemble", requests=len(reqs),
                  links=links):
            for req in reqs:
                feed, err = (try_extract(self.extract, req.payload)
                             if self.extract is not None
                             else (req.payload, None))
                if err is not None:
                    self._finish(req, error=err)
                    continue
                feeds.append(feed)
                live.append(req)
        if not live:
            return
        try:
            fut = self._submit(feeds)
        except Exception as e:
            self._complete_failed(live, feeds, e)
            return
        self._pending.append((live, feeds, fut, batch_ctx))
        self._resolve_pending(self.max_inflight - 1)

    def _submit(self, feeds: list[dict[str, np.ndarray]]):
        """Stack + dispatch one micro-batch; returns a result future.
        Async when the runner supports it (the readback then overlaps
        the next assembly/dispatch), degrading to an already-resolved
        wrapper around the blocking call otherwise."""
        keys = feeds[0].keys()
        if any(f.keys() != keys for f in feeds):
            raise ValueError("requests disagree on feature keys")
        arrays = {k: np.stack([np.asarray(f[k]) for f in feeds]) for k in keys}
        submit_async = getattr(self.runner, "run_batch_async", None)
        if submit_async is not None:
            return submit_async(arrays)
        return _Resolved(self.runner.run_batch(arrays))

    def _resolve_pending(self, limit: int) -> None:
        """Collect completed dispatches (submission order) until at most
        ``limit`` stay in flight."""
        while len(self._pending) > limit:
            live, feeds, fut, ctx = self._pending.popleft()
            with tracing.attach(ctx):
                try:
                    # sparkdl-lint: disable=blocking-in-hot-loop -- resolution is guaranteed: BatchResult resolves with its dispatch, _Work by the pool's first-writer-wins/_fail_inflight invariants (PR 5); a timeout here would fail healthy slow batches
                    outs = fut.result()
                except Exception as e:
                    self._complete_failed(live, feeds, e)
                    continue
                self.metrics.record_batch(len(live), self.runner.chunk_size)
                for i, req in enumerate(live):
                    self._finish(req, result=_row(outs, i))

    def _complete_failed(self, live: list[Request],
                         feeds: list[dict[str, np.ndarray]],
                         e: Exception) -> None:
        if len(live) == 1:
            self._finish(live[0], error=e)
            return
        # poison-row fallback: one bad row must not take down its
        # batch-mates — retry each row alone, only the culprit errors
        _log.warning(
            "batch of %d failed; retrying per-row", len(live),
            exc_info=True,
        )
        for req, feed in zip(live, feeds):
            # each retry is a real device dispatch: count it, at its
            # honest 1-row occupancy, so a poison-row storm shows up
            # in the metrics instead of hiding behind them
            self.metrics.record_batch(1, self.runner.chunk_size)
            try:
                out = self._submit([feed]).result()
                self._finish(req, result=_row(out, 0))
            except Exception as row_e:
                self._finish(req, error=row_e)

    def _fail_inflight(self, exc: "Exception | None" = None) -> None:
        """Fail every dispatched-but-unresolved request (crashed loop /
        watchdog shutdown): no Future may be left unresolved."""
        if exc is None:
            from sparkdl_tpu.serving.queue import EngineClosedError

            exc = EngineClosedError("engine shut down mid-dispatch")
        while self._pending:
            live, _, fut, _ = self._pending.popleft()
            for req in live:
                if not req.future.done():
                    self._finish(req, error=exc)

    def inflight_request_ids(self) -> "list[int]":
        """Request ids of dispatched-but-unresolved batches (postmortem
        input). Best-effort: the loop thread mutates ``_pending``
        concurrently, and a postmortem must never crash serving."""
        out: "list[int]" = []
        try:
            for live, _feeds, _fut, _ctx in list(self._pending):
                out.extend(r.request_id for r in live)
        except RuntimeError:  # pragma: no cover - mutation race
            pass
        return out

    def _finish(self, req: Request, *, result: Any = None,
                error: Exception | None = None) -> None:
        now = time.monotonic()
        latency = now - req.enqueued
        if tracing.tracing_enabled():
            # the request's terminal span: submit -> resolution, rooted
            # on its own trace (the full lifetime, queue wait included)
            tracing.record_span(
                "serving.request", req.enqueued, now,
                parent=req.trace_ctx, request_id=req.request_id,
                ok=error is None,
                **({"error": type(error).__name__} if error else {}),
            )
        if error is not None:
            # shed load must be observable: every accepted-then-failed
            # request lands in the reason-labelled registry counter
            record_request_failure(error, request_id=req.request_id)
            req.future.set_exception(error)
        else:
            req.future.set_result(result)
        self.metrics.record_request(latency, ok=error is None)
        reg = getattr(self.queue, "tenants", None)
        if reg is not None:
            reg.note_outcome(req.tenant, latency, ok=error is None)


def _row(out, i: int):
    """Row ``i`` of a run_batch output (array or tuple of arrays)."""
    if isinstance(out, tuple):
        return tuple(o[i] for o in out)
    return out[i]
