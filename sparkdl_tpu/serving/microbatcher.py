"""Dynamic micro-batching dispatch loop over a BatchedRunner.

The chip-saturation half of the serving engine: individual requests (one
row each) coalesce into the bucketed, jit-cached device batches the batch
pipeline already compiles (``transformers/_inference.BatchedRunner`` —
including its automatic dp sharding on multi-chip hosts). Policy is the
classic max-wait/max-batch: the first request in an empty queue waits at
most ``max_wait_s`` before dispatch; every request that arrives in that
window rides the same device program for free.

Robustness contract: a bad request degrades to ITS error, never the
batch's. Extraction failures (shared :func:`try_extract` convention) fail
per request before stacking; a dispatch failure of a multi-row batch
falls back to per-row dispatch so healthy neighbors of a poison row still
get results.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

import numpy as np

from sparkdl_tpu.observability import tracing
from sparkdl_tpu.observability.tracing import span
from sparkdl_tpu.serving.metrics import ServingMetrics
from sparkdl_tpu.serving.queue import Request, RequestQueue
from sparkdl_tpu.transformers._inference import BatchedRunner, try_extract

_log = logging.getLogger(__name__)


class MicroBatcher:
    """Drains a :class:`RequestQueue` into ``runner.run_batch`` dispatches.

    ``extract`` (optional) maps a request payload to the feature dict the
    runner eats — same role as the partition path's extract, same
    per-row-error semantics. Without it, payloads must already be feature
    dicts of per-row arrays (no batch dim; the batcher stacks).
    """

    def __init__(self, queue: RequestQueue, runner: BatchedRunner, *,
                 max_wait_s: float = 0.005,
                 extract: Callable[[Any], dict[str, np.ndarray]] | None = None,
                 metrics: ServingMetrics | None = None):
        self.queue = queue
        self.runner = runner
        self.max_wait_s = max_wait_s
        self.extract = extract
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(
            target=self._loop, name="sparkdl-microbatcher", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, *, drain: bool = True,
                 timeout_s: float | None = 30.0) -> None:
        """Stop the loop. ``drain=True`` (graceful): close admission,
        serve everything already queued, then stop. ``drain=False``: fail
        queued requests with EngineClosedError and stop now."""
        self.queue.close()
        if not drain:
            self.queue.fail_pending()
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            if self._thread.is_alive():  # pragma: no cover - watchdog only
                _log.warning("micro-batcher did not stop in %ss", timeout_s)
        elif drain:  # never started: drain inline so no future is stranded
            while True:
                reqs = self.queue.take(self.runner.chunk_size, 0.0)
                if not reqs:
                    break
                self._dispatch(reqs)
        self._stop.set()
        # a timed-out join or crashed loop may leave queued requests
        # behind: no Future may ever be left unresolved
        self.queue.fail_pending()

    # -- dispatch ------------------------------------------------------------
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                reqs = self.queue.take(self.runner.chunk_size,
                                       self.max_wait_s)
                if not reqs:
                    if self.queue.closed and self.queue.depth == 0:
                        break  # graceful drain complete
                    continue
                self._dispatch(reqs)
        except BaseException as e:
            # _dispatch contains per-batch error handling; anything that
            # escapes is fatal — fail the queue rather than strand callers
            exc = (e if isinstance(e, Exception)
                   else RuntimeError(f"micro-batcher loop died: {e!r}"))
            self.queue.close()
            self.queue.fail_pending(exc)
            raise

    def _dispatch(self, reqs: list[Request]) -> None:
        # The worker thread has no ambient span; re-root on the first
        # rider's submit-side context so batch-assembly and device-step
        # spans land in a caller's trace (cross-thread contextvar hop).
        batch_ctx = next(
            (r.trace_ctx for r in reqs if r.trace_ctx is not None), None
        )
        with tracing.attach(batch_ctx):
            self._dispatch_traced(reqs)

    def _dispatch_traced(self, reqs: list[Request]) -> None:
        feeds: list[dict[str, np.ndarray]] = []
        live: list[Request] = []
        with span("serving.batch_assemble", requests=len(reqs)):
            for req in reqs:
                feed, err = (try_extract(self.extract, req.payload)
                             if self.extract is not None
                             else (req.payload, None))
                if err is not None:
                    self._finish(req, error=err)
                    continue
                feeds.append(feed)
                live.append(req)
        if not live:
            return
        try:
            outs = self._run(feeds)
        except Exception as e:
            if len(live) == 1:
                self._finish(live[0], error=e)
                return
            # poison-row fallback: one bad row must not take down its
            # batch-mates — retry each row alone, only the culprit errors
            _log.warning(
                "batch of %d failed; retrying per-row", len(live),
                exc_info=True,
            )
            for req, feed in zip(live, feeds):
                # each retry is a real device dispatch: count it, at its
                # honest 1-row occupancy, so a poison-row storm shows up
                # in the metrics instead of hiding behind them
                self.metrics.record_batch(1, self.runner.chunk_size)
                try:
                    out = self._run([feed])
                    self._finish(req, result=_row(out, 0))
                except Exception as row_e:
                    self._finish(req, error=row_e)
            return
        self.metrics.record_batch(len(live), self.runner.chunk_size)
        for i, req in enumerate(live):
            self._finish(req, result=_row(outs, i))

    def _run(self, feeds: list[dict[str, np.ndarray]]):
        keys = feeds[0].keys()
        if any(f.keys() != keys for f in feeds):
            raise ValueError("requests disagree on feature keys")
        arrays = {k: np.stack([np.asarray(f[k]) for f in feeds]) for k in keys}
        return self.runner.run_batch(arrays)

    def _finish(self, req: Request, *, result: Any = None,
                error: Exception | None = None) -> None:
        latency = time.monotonic() - req.enqueued
        if error is not None:
            req.future.set_exception(error)
        else:
            req.future.set_result(result)
        self.metrics.record_request(latency, ok=error is None)


def _row(out, i: int):
    """Row ``i`` of a run_batch output (array or tuple of arrays)."""
    if isinstance(out, tuple):
        return tuple(o[i] for o in out)
    return out[i]
