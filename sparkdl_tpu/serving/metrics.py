"""Serving observability: queue depth, batch occupancy, latency tails.

Built on :mod:`sparkdl_tpu.observability.metrics` — per-request latency
rides a :class:`StepMeter` window so the p50/p95/p99 helpers are the SAME
code that meters training steps (one percentile implementation in the
whole stack), and counters mirror the queue's admission bookkeeping.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any

from sparkdl_tpu.observability import flight
from sparkdl_tpu.observability import slo as slo_mod
from sparkdl_tpu.observability.metrics import StepMeter
from sparkdl_tpu.observability.registry import PERCENT_BUCKETS, registry

# The registry spine's view of every ServingMetrics instance in the
# process (engines aggregate; per-engine detail stays on snapshot()).
_M_REQS = registry().counter(
    "sparkdl_serving_requests_total", "finished requests by outcome",
    labels=("outcome",))
_M_REQ_OK = _M_REQS.labels(outcome="completed")
_M_REQ_FAIL = _M_REQS.labels(outcome="failed")
_M_LATENCY = registry().histogram(
    "sparkdl_serving_latency_seconds", "request latency, submit to result")
_M_BATCHES = registry().counter(
    "sparkdl_serving_batches_total", "device dispatches")
_M_OCCUPANCY = registry().histogram(
    "sparkdl_serving_batch_occupancy_pct",
    "live rows per dispatch as % of capacity", buckets=PERCENT_BUCKETS)


def default_host_id() -> str:
    """The stable id a serving engine publishes in ``snapshot()`` so a
    router tier can address this host (ISSUE 14). Operators pin it via
    ``SPARKDL_TPU_HOST_ID`` (a k8s pod name, an instance id); the
    default ``hostname:pid`` is unique per serving process, which is
    what the fabric's in-process test hosts and single-host deployments
    need. Engines may also take ``host_id=`` directly (how several
    in-process hosts in one test process stay distinct)."""
    env = os.environ.get("SPARKDL_TPU_HOST_ID")
    return env if env else f"{socket.gethostname()}:{os.getpid()}"


class EngineObservability:
    """The process-wide registrations every serving engine shares
    (ISSUE 9): an optional SLO tracker, a flight-recorder context
    provider, and engine.start/engine.close lifecycle events. One
    implementation so ServingEngine and ContinuousGPTEngine cannot
    drift. Construct LAST in the engine's ``__init__`` (a constructor
    failure must not leak registrations) and :meth:`close` on engine
    close (idempotent)."""

    def __init__(self, kind: str, context_fn, *,
                 slo: "slo_mod.SLO | None" = None, **start_fields):
        self.tracker = (
            slo_mod.register(slo_mod.SLOTracker(slo))
            if slo is not None else None
        )
        self.name = flight.add_context_provider(
            f"{kind}-{id(context_fn.__self__):x}", context_fn
        )
        self._closed = False
        flight.record_event("engine.start", engine=self.name,
                            **start_fields)

    def close(self, *, drain: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        flight.record_event("engine.close", engine=self.name, drain=drain)
        flight.remove_context_provider(self.name)
        if self.tracker is not None:
            slo_mod.unregister(self.tracker)


class ServingMetrics:
    """Thread-safe counters + windowed latency/occupancy for one engine.

    ``snapshot()`` is the structured dict an operator scrapes: admission
    (submitted/rejected/expired/cancelled, straight off the queue's own
    counters), outcomes (completed/failed), queue depth, mean
    batch-occupancy %, dispatch count, and request latency p50/p95/p99
    (seconds, submit -> result).
    """

    def __init__(self, window: int = 1024):
        self._lock = threading.Lock()
        # n_chips=1: latency is per request, not per chip; warmup 0 —
        # serving must count the compile-paying first requests too.
        self._latency = StepMeter(n_chips=1, window=window, warmup_steps=0)
        self._occupancy = StepMeter(n_chips=1, window=window, warmup_steps=0)
        self.completed = 0
        self.failed = 0
        self.batches = 0

    def record_request(self, latency_s: float, *, ok: bool) -> None:
        with self._lock:
            self._latency.record(latency_s, examples=1)
            if ok:
                self.completed += 1
            else:
                self.failed += 1
        _M_LATENCY.observe(latency_s)
        (_M_REQ_OK if ok else _M_REQ_FAIL).inc()

    def record_batch(self, n_valid: int, capacity: int) -> None:
        """One device dispatch: ``n_valid`` live rows of ``capacity``
        (bucket size or slot count) — occupancy is what dynamic batching
        is buying over batch-of-1."""
        with self._lock:
            self.batches += 1
            if capacity > 0:
                self._occupancy.record(100.0 * n_valid / capacity,
                                       examples=n_valid)
        _M_BATCHES.inc()
        if capacity > 0:
            _M_OCCUPANCY.observe(100.0 * n_valid / capacity)

    def latency_percentiles(self) -> dict[str, float | None]:
        with self._lock:
            return self._latency.step_time_percentiles((50, 95, 99))

    def snapshot(self, queue=None) -> dict[str, Any]:
        """Point-in-time metrics dict; pass the engine's RequestQueue to
        include its depth and admission counters."""
        with self._lock:
            out: dict[str, Any] = {
                "completed": self.completed,
                "failed": self.failed,
                "batches": self.batches,
                "batch_occupancy_pct": self._occupancy.mean_step_time(),
                "latency_s": self._latency.step_time_percentiles((50, 95, 99)),
                "latency_mean_s": self._latency.mean_step_time(),
            }
        if queue is not None:
            out.update(
                queue_depth=queue.depth,
                submitted=queue.submitted,
                rejected=queue.rejected,
                expired=queue.expired,
                cancelled=queue.cancelled,
            )
        return out
